"""Weight interop: HuggingFace Llama-family checkpoints -> shifu_tpu.

``from_hf_llama`` maps a `transformers` Llama model (or its config +
state_dict) onto the native Transformer family so existing checkpoints
can be served/fine-tuned on TPU without retraining. The numerical
conventions line up exactly (verified by the parity test in
tests/test_convert.py against the torch forward):

  * RoPE: both use the split-half (rotate_half) convention with
    inv_freq = theta^(-2i/head_dim) — weights transfer unpermuted.
  * RMSNorm: HF stores the full gain g (y = x̂·g); this framework stores
    (1 + scale) — so ``scale = g - 1``.
  * Linear layers: torch keeps (out, in); einsum weights here are
    (in, out[, ...]) — transpose + reshape, heads-major.
  * MoE (Mixtral layout, round 5): ``block_sparse_moe.gate`` is the
    router ((E, d) -> (d, E)); expert e's ``w1/w3/w2`` are SwiGLU
    gate/up/down, stacked over experts into the (L, E, ...) leaves.
    Routing semantics already agree (softmax over all experts, top-k,
    renormalise — ops.moe route_top_k's Mixtral convention); HF never
    drops tokens, so conversion sets moe_capacity_factor = n_experts
    (provably dropless: capacity >= s*k even if every token picks one
    expert) — override it to serve with real capacity limits.

Everything is stacked across layers into the (layers, ...) leaves the
scan-based forward expects.
"""

from __future__ import annotations

from typing import Any, Mapping, Tuple

import numpy as np

import jax.numpy as jnp

from shifu_tpu.models.transformer import Transformer, TransformerConfig


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def config_from_hf_llama(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig mirroring a transformers LlamaConfig."""
    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type"))
        if rope_type == "llama3":
            rope_scaling = (
                "llama3",
                float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                int(scaling["original_max_position_embeddings"]),
            )
        elif rope_type == "linear":
            rope_scaling = ("linear", float(scaling["factor"]))
        elif rope_type == "dynamic":
            rope_scaling = (
                "dynamic",
                float(scaling["factor"]),
                # HF's _compute_dynamic_ntk_parameters stretches relative
                # to max_position_embeddings UNconditionally — the
                # original_max_position_embeddings key is validated but
                # unused there (explicit TODO in HF); honoring it here
                # would silently diverge from the torch forward.
                int(hf_config.max_position_embeddings),
            )
        elif rope_type == "yarn":
            from shifu_tpu.ops.rope import get_mscale

            # attention_factor resolution order mirrors HF: explicit >
            # mscale/mscale_all_dim pair (DeepSeek convention) > derived
            # from factor inside rope_frequencies (None).
            attn_factor = scaling.get("attention_factor")
            mscale = scaling.get("mscale")
            mscale_all = scaling.get("mscale_all_dim")
            if attn_factor is None and mscale and mscale_all:
                factor = float(scaling["factor"])
                attn_factor = get_mscale(factor, mscale) / get_mscale(
                    factor, mscale_all
                )
            rope_scaling = (
                "yarn",
                float(scaling["factor"]),
                float(scaling.get("beta_fast") or 32.0),
                float(scaling.get("beta_slow") or 1.0),
                int(
                    scaling.get("original_max_position_embeddings")
                    or hf_config.max_position_embeddings
                ),
                None if attn_factor is None else float(attn_factor),
                bool(scaling.get("truncate", True)),
            )
        elif rope_type == "longrope":
            # HF quirk (Phi-3): a config-level original_max_position_
            # embeddings both sets the short/long switch point AND
            # overrides rope_scaling["factor"] with the max/original
            # ratio for the default attention factor.
            orig = getattr(
                hf_config, "original_max_position_embeddings", None
            )
            if orig:
                factor = hf_config.max_position_embeddings / orig
            else:
                orig = hf_config.max_position_embeddings
                if scaling.get("factor") is None:
                    # HF's longrope validation requires `factor` in this
                    # case; silently defaulting would change the
                    # attention scale vs any torch reference.
                    raise ValueError(
                        "longrope needs rope_scaling['factor'] when the "
                        "config has no original_max_position_embeddings"
                    )
                factor = float(scaling["factor"])
            attn_factor = scaling.get("attention_factor")
            rope_scaling = (
                "longrope",
                tuple(float(f) for f in scaling["short_factor"]),
                tuple(float(f) for f in scaling["long_factor"]),
                int(orig),
                float(factor),
                None if attn_factor is None else float(attn_factor),
            )
        elif rope_type != "default":
            raise NotImplementedError(
                f"rope_scaling type {rope_type!r} is not supported "
                "(implemented: default, linear, dynamic, yarn, llama3, "
                "longrope)"
            )
    moe_kw = {}
    n_experts = getattr(hf_config, "num_local_experts", 0) or 0
    if n_experts:
        moe_kw = dict(
            n_experts=int(n_experts),
            moe_top_k=int(hf_config.num_experts_per_tok),
            # Dropless parity with the HF forward (module docstring).
            moe_capacity_factor=float(n_experts),
        )
    kw = dict(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        **moe_kw,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None)
        or hf_config.num_attention_heads,
        mlp_dim=hf_config.intermediate_size,
        head_dim=getattr(hf_config, "head_dim", None),
        rope_theta=getattr(hf_config, "rope_theta", 10_000.0),
        rope_scaling=rope_scaling,
        norm_eps=hf_config.rms_norm_eps,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        # Qwen2 hardcodes q/k/v biases (no o bias); Llama-family configs
        # say so via attention_bias. attention_bias=True on an actual
        # LlamaConfig ALSO biases o_proj, which this layout does not
        # carry — params_from_hf_llama then fails loudly on the
        # unconsumed o_proj.bias tensors rather than dropping them.
        qkv_bias=(
            bool(getattr(hf_config, "attention_bias", False))
            or getattr(hf_config, "model_type", "") == "qwen2"
        ),
        # Qwen2-style configs carry sliding_window but gate it off with
        # use_sliding_window=False — honoring the value unconditionally
        # would silently diverge from the HF forward at long context.
        window_size=(
            getattr(hf_config, "sliding_window", None)
            if getattr(hf_config, "use_sliding_window", True)
            else None
        ),
    )
    model_type = getattr(hf_config, "model_type", "")
    if model_type == "gemma":
        # Gemma-1: the Llama block shape with the Gemma conventions —
        # GeGLU, sqrt(dim) embedding scale, zero-centred norm gains,
        # explicit head_dim, tied embeddings (from the config). The HF
        # forward keys the activation off hidden_act (GemmaMLP uses
        # ACT2FN[config.hidden_act]) — and the ORIGINAL Hub configs
        # carry "gelu", which is the exact erf gelu, not the tanh
        # approximation; mapping it to gelu_tanh would silently break
        # logits parity.
        act = getattr(hf_config, "hidden_act", "gelu_pytorch_tanh")
        if act in ("gelu_pytorch_tanh", "gelu_tanh"):
            mlp_act = "gelu_tanh"
        elif act == "gelu":
            mlp_act = "gelu_erf"
        else:
            raise NotImplementedError(
                f"gemma hidden_act {act!r} (expected a gelu variant)"
            )
        kw.update(
            mlp_act=mlp_act, embed_scale=True,
            zero_centered_hf_norms=True,
        )
    if model_type == "qwen3":
        # Qwen3 = the Llama layout + per-head q/k RMS norms, no qkv
        # biases (attention_bias False is the config default — handled
        # by the generic qkv_bias line above).
        kw["qk_norm"] = True
    if model_type == "gemma2":
        act = getattr(hf_config, "hidden_activation", "gelu_pytorch_tanh")
        if act not in ("gelu_pytorch_tanh", "gelu_tanh"):
            raise NotImplementedError(
                f"gemma2 hidden_activation {act!r} (expected "
                "gelu_pytorch_tanh)"
            )
        kw.update(
            zero_centered_hf_norms=True,
            attn_softcap=(
                None
                if hf_config.attn_logit_softcapping is None
                else float(hf_config.attn_logit_softcapping)
            ),
            final_softcap=(
                None
                if hf_config.final_logit_softcapping is None
                else float(hf_config.final_logit_softcapping)
            ),
            attn_scale=float(hf_config.query_pre_attn_scalar),
            mlp_act="gelu_tanh",
            post_norms=True,
            embed_scale=True,
            # The flash kernel handles both Gemma-2 attention quirks
            # natively (tanh softcap inside the online softmax,
            # per-layer windows via static-window branches), so the
            # family converts straight onto the fast path; pass
            # attn_impl="xla" in overrides for the parity oracle.
            attn_impl="flash",
            # Sliding attention on EVEN layers, full on odd
            # (layer_types in the HF config; the alternation is the
            # architecture, pattern 2 with offset 0).
            window_pattern=2 if hf_config.sliding_window else None,
        )
        lt = getattr(hf_config, "layer_types", None)
        if lt is not None and hf_config.sliding_window:
            want = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(len(lt))
            ]
            if list(lt) != want:
                raise NotImplementedError(
                    "gemma2 layer_types deviates from the alternating "
                    "even-sliding pattern window_pattern=2 encodes: "
                    f"{list(lt)[:6]}..."
                )
    kw.update(overrides)
    return TransformerConfig(**kw)


def params_from_hf_llama(
    state_dict: Mapping[str, Any], cfg: TransformerConfig, dtype=jnp.float32,
    *, zero_centered_norms: Optional[bool] = None,
):
    """shifu_tpu param tree from a HF Llama state_dict.

    ``zero_centered_norms``: the checkpoint stores RMS gains as 1+w
    (the Gemma convention) rather than the full gain (Llama). Defaults
    to ``cfg.zero_centered_hf_norms or cfg.post_norms`` — configs from
    config_from_hf_llama carry the convention flag, and hand-built
    Gemma-2-shaped configs (post_norms) still default right; the
    kwarg remains for callers converting checkpoints whose convention
    deviates from their config."""
    sd = {k: v for k, v in state_dict.items()}
    L = cfg.n_layers
    d, h, kv, hd = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.resolved_head_dim,
    )
    consumed = set()

    def get(name):
        for prefix in ("model.", ""):
            key = prefix + name
            if key in sd:
                consumed.add(key)
                return _to_np(sd[key])
        raise KeyError(f"missing weight {name!r} in state_dict")

    def stack(fmt, transform):
        return jnp.asarray(
            np.stack([transform(get(fmt.format(l))) for l in range(L)]),
            dtype,
        )

    # Norm-gain convention: Llama-family HF norms store the FULL gain
    # (our zero-centred storage subtracts 1); Gemma-family norms
    # already store 1+w zero-centred — no shift (docstring). The
    # post_norms flag additionally renames the FFN norms
    # (post_attention_layernorm is the attention SANDWICH norm in the
    # Gemma-2 block, not the pre-FFN norm).
    if zero_centered_norms is None:
        zero_centered_norms = cfg.zero_centered_hf_norms or cfg.post_norms
    nsub = 0.0 if zero_centered_norms else 1.0
    blocks = {
        "attn_norm": stack(
            "layers.{}.input_layernorm.weight", lambda w: w - nsub
        ),
        "mlp_norm": stack(
            "layers.{}.pre_feedforward_layernorm.weight"
            if cfg.post_norms
            else "layers.{}.post_attention_layernorm.weight",
            lambda w: w - nsub,
        ),
        # torch Linear weight (out, in): transpose, then split the out dim
        # heads-major.
        "wq": stack(
            "layers.{}.self_attn.q_proj.weight",
            lambda w: w.T.reshape(d, h, hd),
        ),
        "wk": stack(
            "layers.{}.self_attn.k_proj.weight",
            lambda w: w.T.reshape(d, kv, hd),
        ),
        "wv": stack(
            "layers.{}.self_attn.v_proj.weight",
            lambda w: w.T.reshape(d, kv, hd),
        ),
        "wo": stack(
            "layers.{}.self_attn.o_proj.weight",
            lambda w: w.T.reshape(h, hd, d),
        ),
    }
    if cfg.n_experts:
        E = cfg.n_experts

        def estack(fmt):
            # (L, E, ...) leaves: experts inner, layers outer.
            return jnp.asarray(
                np.stack([
                    np.stack([
                        get(fmt.format(l, e)).T for e in range(E)
                    ])
                    for l in range(L)
                ]),
                dtype,
            )

        blocks["router"] = stack(
            "layers.{}.block_sparse_moe.gate.weight", lambda w: w.T
        )
        # Mixtral expert naming: w1 = SwiGLU gate, w3 = up, w2 = down.
        blocks["w_gate"] = estack(
            "layers.{}.block_sparse_moe.experts.{}.w1.weight"
        )
        blocks["w_up"] = estack(
            "layers.{}.block_sparse_moe.experts.{}.w3.weight"
        )
        blocks["w_down"] = estack(
            "layers.{}.block_sparse_moe.experts.{}.w2.weight"
        )
    else:
        blocks["w_gate"] = stack(
            "layers.{}.mlp.gate_proj.weight", lambda w: w.T
        )
        blocks["w_up"] = stack("layers.{}.mlp.up_proj.weight", lambda w: w.T)
        blocks["w_down"] = stack(
            "layers.{}.mlp.down_proj.weight", lambda w: w.T
        )
    if cfg.post_norms:
        blocks["post_attn_norm"] = stack(
            "layers.{}.post_attention_layernorm.weight",
            lambda w: w - nsub,
        )
        blocks["post_mlp_norm"] = stack(
            "layers.{}.post_feedforward_layernorm.weight",
            lambda w: w - nsub,
        )
    if cfg.qk_norm:
        blocks["q_norm"] = stack(
            "layers.{}.self_attn.q_norm.weight", lambda w: w - 1.0
        )
        blocks["k_norm"] = stack(
            "layers.{}.self_attn.k_norm.weight", lambda w: w - 1.0
        )
    if cfg.qkv_bias:
        blocks["bq"] = stack(
            "layers.{}.self_attn.q_proj.bias", lambda b: b.reshape(h, hd)
        )
        blocks["bk"] = stack(
            "layers.{}.self_attn.k_proj.bias", lambda b: b.reshape(kv, hd)
        )
        blocks["bv"] = stack(
            "layers.{}.self_attn.v_proj.bias", lambda b: b.reshape(kv, hd)
        )
    params = {
        "embed": jnp.asarray(get("embed_tokens.weight"), dtype),
        "blocks": blocks,
        "final_norm": jnp.asarray(get("norm.weight") - nsub, dtype),
    }
    if not cfg.tie_embeddings:
        params["unembed"] = jnp.asarray(get("lm_head.weight").T, dtype)

    # Every remaining tensor would be silently dropped — for a model with
    # e.g. attention biases (Qwen2-style) that means numerically wrong
    # logits with no error. Fail loudly instead. (Rotary inv_freq buffers
    # are derived constants, safe to skip; a tied lm_head aliases embed.)
    def ignorable(k):
        return k.endswith("rotary_emb.inv_freq") or (
            cfg.tie_embeddings and k == "lm_head.weight"
        )

    leftover = sorted(
        k for k in sd if k not in consumed and not ignorable(k)
    )
    if leftover:
        raise ValueError(
            f"{len(leftover)} state_dict tensors were not consumed by the "
            f"Llama layout (first few: {leftover[:4]}); this checkpoint "
            "has weights (e.g. biases) the conversion does not map"
        )
    return params


def to_hf_llama_state_dict(params, cfg: TransformerConfig,
                           *, zero_centered_norms: Optional[bool] = None):
    """shifu_tpu params -> HF Llama-layout state_dict (numpy tensors).

    Exact inverse of :func:`params_from_hf_llama` (round-trip tested), so
    TPU-trained weights load into `transformers` for publication or
    GPU serving: ``LlamaForCausalLM(config).load_state_dict({k:
    torch.from_numpy(v) for k, v in sd.items()})``. With
    ``cfg.qkv_bias`` the export carries q/k/v (not o) bias keys — the
    Qwen2 convention — so load it into ``Qwen2ForCausalLM``; Llama's
    ``attention_bias=True`` expects an o_proj bias this layout does not
    have.
    """
    L = cfg.n_layers
    d, h, kv, hd = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.resolved_head_dim,
    )
    blocks = params["blocks"]

    def np_(x):
        return np.asarray(x, np.float32)

    if zero_centered_norms is None:  # params_from_hf_llama docstring
        zero_centered_norms = cfg.zero_centered_hf_norms or cfg.post_norms
    nsub = 0.0 if zero_centered_norms else 1.0
    sd = {"model.embed_tokens.weight": np_(params["embed"])}
    for l in range(L):
        p = f"model.layers.{l}."
        sd[p + "input_layernorm.weight"] = np_(blocks["attn_norm"][l]) + nsub
        if cfg.post_norms:
            sd[p + "pre_feedforward_layernorm.weight"] = (
                np_(blocks["mlp_norm"][l]) + nsub
            )
            sd[p + "post_attention_layernorm.weight"] = (
                np_(blocks["post_attn_norm"][l]) + nsub
            )
            sd[p + "post_feedforward_layernorm.weight"] = (
                np_(blocks["post_mlp_norm"][l]) + nsub
            )
        else:
            sd[p + "post_attention_layernorm.weight"] = (
                np_(blocks["mlp_norm"][l]) + nsub
            )
        if cfg.qk_norm:
            sd[p + "self_attn.q_norm.weight"] = (
                np_(blocks["q_norm"][l]) + 1.0
            )
            sd[p + "self_attn.k_norm.weight"] = (
                np_(blocks["k_norm"][l]) + 1.0
            )
        sd[p + "self_attn.q_proj.weight"] = (
            np_(blocks["wq"][l]).reshape(d, h * hd).T
        )
        sd[p + "self_attn.k_proj.weight"] = (
            np_(blocks["wk"][l]).reshape(d, kv * hd).T
        )
        sd[p + "self_attn.v_proj.weight"] = (
            np_(blocks["wv"][l]).reshape(d, kv * hd).T
        )
        sd[p + "self_attn.o_proj.weight"] = (
            np_(blocks["wo"][l]).reshape(h * hd, d).T
        )
        if cfg.n_experts:
            moe = p + "block_sparse_moe."
            sd[moe + "gate.weight"] = np_(blocks["router"][l]).T
            for e in range(cfg.n_experts):
                ex = moe + f"experts.{e}."
                sd[ex + "w1.weight"] = np_(blocks["w_gate"][l, e]).T
                sd[ex + "w3.weight"] = np_(blocks["w_up"][l, e]).T
                sd[ex + "w2.weight"] = np_(blocks["w_down"][l, e]).T
        else:
            sd[p + "mlp.gate_proj.weight"] = np_(blocks["w_gate"][l]).T
            sd[p + "mlp.up_proj.weight"] = np_(blocks["w_up"][l]).T
            sd[p + "mlp.down_proj.weight"] = np_(blocks["w_down"][l]).T
        if cfg.qkv_bias:
            sd[p + "self_attn.q_proj.bias"] = np_(blocks["bq"][l]).reshape(
                h * hd
            )
            sd[p + "self_attn.k_proj.bias"] = np_(blocks["bk"][l]).reshape(
                kv * hd
            )
            sd[p + "self_attn.v_proj.bias"] = np_(blocks["bv"][l]).reshape(
                kv * hd
            )
    sd["model.norm.weight"] = np_(params["final_norm"]) + nsub
    if cfg.tie_embeddings:
        # torch state_dicts list tied params under BOTH names; omitting
        # lm_head.weight would fail the documented load_state_dict call.
        sd["lm_head.weight"] = np_(params["embed"])
    else:
        sd["lm_head.weight"] = np_(params["unembed"]).T
    return sd


# ---------------------------------------------------- Mamba (SSM) family


def config_from_hf_mamba(hf_config, **overrides):
    """MambaConfig mirroring a transformers MambaConfig (round 5 — the
    SSM family stops being synthetic-weights-only). ``time_step_rank``
    "auto" resolves to ceil(hidden/16), matching both sides' default.
    Projection biases (``use_bias``) and conv-without-bias
    (``use_conv_bias=False``) have no native layout here — refused
    loudly rather than silently dropped."""
    from shifu_tpu.models.mamba import MambaConfig

    if getattr(hf_config, "use_bias", False):
        raise NotImplementedError(
            "use_bias=True (in/out projection biases) has no native "
            "Mamba layout here"
        )
    if not getattr(hf_config, "use_conv_bias", True):
        raise NotImplementedError(
            "use_conv_bias=False checkpoints are unsupported (the "
            "native layout always carries conv_b; a zero bias would "
            "load, but refusing is safer than guessing)"
        )
    tsr = getattr(hf_config, "time_step_rank", "auto")
    kw = dict(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        d_state=hf_config.state_size,
        d_conv=hf_config.conv_kernel,
        expand=hf_config.expand,
        dt_rank=None if tsr == "auto" else int(tsr),
        norm_eps=hf_config.layer_norm_epsilon,
    )
    kw.update(overrides)
    return MambaConfig(**kw)


def params_from_hf_mamba(state_dict, cfg, dtype=jnp.float32):
    """shifu_tpu Mamba param tree from a HF Mamba state_dict.

    Numerics line up exactly (tests/test_convert.py parity vs the
    torch slow path): both sides split in_proj [x | gate], compute
    dt = softplus(x_proj_dt @ dt_proj + bias), discretise
    dA = exp(dt·(-exp(A_log))), dB = dt·B, and gate y·silu(z). HF's
    fused ``x_proj`` (dt_rank + 2·state rows) splits into the native
    dt_down / x_B / x_C leaves; conv1d (di, 1, k) transposes to the
    (k, di) depthwise layout; RMSNorm gains convert full-g -> g-1."""
    import numpy as np  # noqa: F811 (local alias for stacking)

    sd = dict(state_dict)
    L = cfg.n_layers
    r, n = cfg.resolved_dt_rank, cfg.d_state
    consumed = set()

    def get(name):
        for prefix in ("backbone.", ""):
            key = prefix + name
            if key in sd:
                consumed.add(key)
                return _to_np(sd[key])
        raise KeyError(f"missing weight {name!r} in state_dict")

    def stack(fmt, transform):
        return jnp.asarray(
            np.stack([transform(get(fmt.format(l))) for l in range(L)]),
            dtype,
        )

    mixer = "layers.{}.mixer."
    blocks = {
        "norm": stack("layers.{}.norm.weight", lambda w: w - 1.0),
        "in_proj": stack(mixer + "in_proj.weight", lambda w: w.T),
        "conv_w": stack(
            mixer + "conv1d.weight", lambda w: w[:, 0, :].T
        ),  # (di, 1, k) -> (k, di)
        "conv_b": stack(mixer + "conv1d.bias", lambda b: b),
        # x_proj rows: [dt_rank | state (B) | state (C)].
        "dt_down": stack(
            mixer + "x_proj.weight", lambda w: w[:r].T
        ),
        "x_B": stack(
            mixer + "x_proj.weight", lambda w: w[r : r + n].T
        ),
        "x_C": stack(
            mixer + "x_proj.weight", lambda w: w[r + n :].T
        ),
        "dt_up": stack(mixer + "dt_proj.weight", lambda w: w.T),
        "dt_bias": stack(mixer + "dt_proj.bias", lambda b: b),
        "A_log": stack(mixer + "A_log", lambda a: a),
        "D": stack(mixer + "D", lambda d_: d_),
        "out_proj": stack(mixer + "out_proj.weight", lambda w: w.T),
    }
    params = {
        "embed": jnp.asarray(get("embeddings.weight"), dtype),
        "blocks": blocks,
        "final_norm": jnp.asarray(get("norm_f.weight") - 1.0, dtype),
    }
    if "lm_head.weight" in sd:
        consumed.add("lm_head.weight")
        params["unembed"] = jnp.asarray(
            _to_np(sd["lm_head.weight"]).T, dtype
        )
    else:  # tied (the state-spaces convention)
        params["unembed"] = jnp.asarray(
            params["embed"].T, dtype
        )
    leftover = sorted(k for k in sd if k not in consumed)
    if leftover:
        raise ValueError(
            f"{len(leftover)} state_dict tensors were not consumed by "
            f"the Mamba layout (first few: {leftover[:4]})"
        )
    return params


def to_hf_mamba_state_dict(params, cfg):
    """shifu_tpu Mamba params -> HF Mamba-layout state_dict (exact
    inverse of :func:`params_from_hf_mamba`, round-trip tested)."""
    import numpy as np  # noqa: F811

    L, r, n = cfg.n_layers, cfg.resolved_dt_rank, cfg.d_state
    blocks = params["blocks"]

    def np_(x):
        return np.asarray(x, np.float32)

    sd = {"backbone.embeddings.weight": np_(params["embed"])}
    for l in range(L):
        p = f"backbone.layers.{l}."
        m = p + "mixer."
        sd[p + "norm.weight"] = np_(blocks["norm"][l]) + 1.0
        sd[m + "in_proj.weight"] = np_(blocks["in_proj"][l]).T
        sd[m + "conv1d.weight"] = np_(blocks["conv_w"][l]).T[:, None, :]
        sd[m + "conv1d.bias"] = np_(blocks["conv_b"][l])
        sd[m + "x_proj.weight"] = np.concatenate(
            [
                np_(blocks["dt_down"][l]).T,
                np_(blocks["x_B"][l]).T,
                np_(blocks["x_C"][l]).T,
            ],
            axis=0,
        )
        sd[m + "dt_proj.weight"] = np_(blocks["dt_up"][l]).T
        sd[m + "dt_proj.bias"] = np_(blocks["dt_bias"][l])
        sd[m + "A_log"] = np_(blocks["A_log"][l])
        sd[m + "D"] = np_(blocks["D"][l])
        sd[m + "out_proj.weight"] = np_(blocks["out_proj"][l]).T
    sd["backbone.norm_f.weight"] = np_(params["final_norm"]) + 1.0
    sd["lm_head.weight"] = np_(params["unembed"]).T
    return sd


def from_hf_mamba(hf_model, dtype=jnp.float32, **config_overrides):
    """(Mamba, params) from a transformers MambaForCausalLM (or any
    module exposing ``.config`` / ``.state_dict()`` in that layout)."""
    from shifu_tpu.models.mamba import Mamba

    cfg = config_from_hf_mamba(hf_model.config, **config_overrides)
    params = params_from_hf_mamba(hf_model.state_dict(), cfg, dtype)
    return Mamba(cfg), params


def from_hf_llama(
    hf_model, dtype=jnp.float32, **config_overrides
) -> Tuple[Transformer, Any]:
    """(Transformer, params) from a transformers Llama(-ForCausalLM) model.

    ``hf_model`` may be any module exposing ``.config`` and
    ``.state_dict()`` with Llama weight names (LlamaForCausalLM,
    MistralForCausalLM, and friends with the same layout).
    """
    cfg = config_from_hf_llama(hf_model.config, **config_overrides)
    # The norm-storage convention rides cfg.zero_centered_hf_norms
    # (set by config_from_hf_llama for the Gemma family).
    params = params_from_hf_llama(hf_model.state_dict(), cfg, dtype)
    return Transformer(cfg), params
