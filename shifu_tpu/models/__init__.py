from shifu_tpu.models.transformer import Transformer, TransformerConfig
from shifu_tpu.models.mamba import Mamba, MambaConfig

__all__ = ["Transformer", "TransformerConfig", "Mamba", "MambaConfig"]
