from shifu_tpu.models.transformer import Transformer, TransformerConfig

__all__ = ["Transformer", "TransformerConfig"]
