from shifu_tpu.models.transformer import Transformer, TransformerConfig
from shifu_tpu.models.mamba import Mamba, MambaConfig
from shifu_tpu.models.convert import (
    config_from_hf_llama,
    from_hf_llama,
    params_from_hf_llama,
    to_hf_llama_state_dict,
)

__all__ = [
    "Transformer",
    "TransformerConfig",
    "Mamba",
    "MambaConfig",
    "config_from_hf_llama",
    "from_hf_llama",
    "params_from_hf_llama",
    "to_hf_llama_state_dict",
]
