"""Flagship decoder-only transformer (GQA + RoPE + SwiGLU + RMSNorm),
optionally MoE (top-k routed experts in every block, expert-parallel over
the ep mesh axis — ops.moe).

TPU-first structural choices:

  * **Scan over layers.** All blocks' parameters are stored *stacked* with a
    leading ("layers",) logical axis, and the forward runs ``lax.scan`` over
    that axis. One block is traced/compiled once regardless of depth, which
    keeps compile times flat, and the stacked axis is exactly what pipeline
    parallelism shards (shifu_tpu.parallel.pipeline).
  * **Logical axes everywhere.** Every parameter dimension carries a logical
    name ("embed", "mlp", "heads", "kv_heads", "head_dim", "vocab",
    "layers"); shifu_tpu.parallel.sharding maps names onto mesh axes
    (tp/fsdp/pp/...) so the model code never mentions devices.
  * **bf16 compute over f32 masters** via core.dtypes.Policy; softmax, norms
    and the final loss reduce in f32.
  * **Static shapes only** — the decode path uses a preallocated KV cache and
    ``dynamic_update_slice``, never growing arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name

from shifu_tpu.core import initializers
from shifu_tpu.core.dtypes import Policy
from shifu_tpu.core.module import Module, ParamSpec
from shifu_tpu.core.qtensor import dequantize_tree, is_qtensor
from shifu_tpu.parallel.ctx import constrain
from shifu_tpu.ops import (
    apply_rope,
    dot_product_attention,
    fused_softmax_cross_entropy,
    moe_capacity,
    rms_norm,
    rope_frequencies,
    route_top_k,
    route_top_k_grouped,
    softmax_cross_entropy,
)
from shifu_tpu.ops.attention import NEG_INF


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    dim: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 4
    mlp_dim: int = 8192
    head_dim: Optional[int] = None  # default: dim // n_heads
    rope_theta: float = 500_000.0
    # Optional RoPE context-extension scaling — a tagged tuple, e.g.
    # ("linear", factor), ("dynamic", factor, orig_len),
    # ("yarn", factor, beta_fast, beta_slow, orig_len, attn_factor),
    # ("llama3", factor, low_freq, high_freq, orig_len); a legacy bare
    # 4-tuple means llama3. Semantics: ops/rope.py module docstring.
    rope_scaling: Optional[tuple] = None
    norm_eps: float = 1e-6
    tie_embeddings: bool = False
    z_loss: float = 1e-4
    remat: bool = True  # rematerialise each block in the backward pass
    # Fused chunked cross-entropy: never materialise (b, s, vocab)
    # logits (see Transformer.loss docstring). Off by default — it
    # trades ~4% step time for gigabytes of HBM headroom.
    fused_ce: bool = False
    # "dots" keeps matmul outputs and recomputes only elementwise ops in
    # the backward pass (~2.5% faster than "full" at equal fit on v5e);
    # "full" recomputes the whole block. "flash" saves ONLY the
    # attention outputs (named "attn_out") — the backward skips
    # re-running the attention forward (the block's quadratic) while
    # still recomputing everything else, costing just (b, s, dim) x
    # n_layers of residency: the policy for models whose "dots" set
    # does not fit (the 1.2B bench case). "dots_flash" combines both
    # (fastest backward, largest residency).
    remat_policy: str = "dots"
    # int8-KV pools only: run the paged-decode kernel's QK score as an
    # s8 x s8 -> s32 MXU dot (q quantized per row, scales applied after
    # the dot) instead of casting K to bf16 in-kernel. BUILT AND
    # MEASURED INERT on v5e at the bench mix (4.71 vs 4.74 ms/step
    # chip-true): the int8->bf16 cast the dot removes was never the
    # int8-KV leg's cost — the per-lane scale streams are (see
    # STATUS.md Known gaps). Default OFF: it adds ~1/127-relative
    # q-rounding error for no measured speed. Top-1 agreement and
    # error bounds are test-pinned either way (tests/test_kv_quant.py).
    int8_qk_dot: bool = False
    # -- mixture of experts (0 experts = dense FFN in every block) ----------
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_lb_coef: float = 0.01  # load-balance aux-loss coefficient
    moe_rz_coef: float = 1e-3  # router z-loss coefficient
    # Expert dispatch implementation: "grouped" (default — sorted
    # inverse-permutation gather into the expert buffers, no dense
    # one-hot einsums; ops.moe module docstring) or "einsum" (the
    # GShard-style (b, s, E, C) dispatch/combine contractions — kept as
    # the bit-auditable correctness oracle; tests pin grouped == einsum
    # across top-k/capacity/drop configs).
    moe_impl: str = "grouped"
    # "xla" | "flash" (pallas TPU kernel) | "ring" (sp sequence
    # parallelism; falls back to xla off-mesh — ops.attention docstring)
    attn_impl: str = "xla"
    # Sliding-window attention (Mistral-style): each query sees at most
    # the last window_size positions. None = full causal attention.
    window_size: Optional[int] = None
    # Biases on the q/k/v projections (Qwen2 convention: qkv yes, o no).
    qkv_bias: bool = False
    # Per-head RMS norm on q and k before rope (Qwen3 convention).
    qk_norm: bool = False
    # -- Gemma-2 family conventions ------------------------------------------
    # tanh soft-capping: scores -> cap * tanh(scores / cap), applied to
    # the attention logits BEFORE the causal mask (attn_softcap) and to
    # the output logits (final_softcap). None = off.
    attn_softcap: Optional[float] = None
    final_softcap: Optional[float] = None
    # Attention score scale DIVISOR override: scores scale by
    # attn_scale**-0.5 instead of head_dim**-0.5 (Gemma-2's
    # query_pre_attn_scalar, which its 9b sets != head_dim).
    attn_scale: Optional[float] = None
    # FFN activation: "silu" (Llama), "gelu_tanh" (Gemma's
    # gelu_pytorch_tanh = jax.nn.gelu(approximate=True)), or
    # "gelu_erf" (exact gelu — original Gemma-1 Hub configs carry
    # hidden_act="gelu", which HF computes UNapproximated).
    mlp_act: str = "silu"
    # INTEROP-ONLY convention marker (no effect on the forward): the
    # HF counterpart of this model stores RMS gains zero-centred
    # (1 + w, the Gemma family) rather than as the full gain (Llama).
    # models/convert keys the ±1 norm shift off it in BOTH directions,
    # so hand-built configs round-trip without remembering a kwarg.
    zero_centered_hf_norms: bool = False
    # Sandwich norms (Gemma-2): extra RMS norms on the attention and
    # FFN OUTPUTS before their residual adds.
    post_norms: bool = False
    # Scale token embeddings by sqrt(dim) (Gemma convention; the
    # normalizer is computed in the activation dtype, matching HF).
    embed_scale: bool = False
    # Alternating sliding-window attention: layer i is windowed iff
    # i % window_pattern == 0 (Gemma-2: pattern 2 — sliding on even
    # layers, full attention on odd). None = window_size (if any)
    # applies to every layer.
    window_pattern: Optional[int] = None
    # Kernel tune-table artifact path (``shifu_tpu tune`` output): when
    # set, the model activates it (ops.pallas.registry.use_table —
    # cached, warn-and-fallback-to-v0 on schema/device mismatch) before
    # every kernel dispatch, so flash-attention block shapes / grid
    # layouts and the MoE dispatch implementation are chosen per shape
    # class by MEASUREMENT instead of the hardcoded defaults. Because
    # resolution is per shape class, an alternating-window stack's two
    # lax.cond branches tune independently — per-layer heterogeneous
    # variants. None = v0 defaults (identical numerics either way; the
    # parity suite pins every variant against v0).
    tune_table: Optional[str] = None

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim if self.head_dim is not None else self.dim // self.n_heads

    def __post_init__(self):
        if self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads={self.n_heads} must be divisible by "
                f"n_kv_heads={self.n_kv_heads}"
            )
        if self.n_experts and self.moe_top_k > self.n_experts:
            raise ValueError(
                f"moe_top_k={self.moe_top_k} exceeds n_experts={self.n_experts}"
            )
        if self.moe_impl not in ("grouped", "einsum"):
            raise ValueError(
                f"moe_impl={self.moe_impl!r} (want 'grouped' or 'einsum')"
            )
        if self.remat_policy not in ("dots", "full", "flash", "dots_flash"):
            raise ValueError(
                f"remat_policy={self.remat_policy!r} (want 'dots', "
                "'full', 'flash', or 'dots_flash')"
            )
        if self.window_size is not None and self.window_size < 1:
            raise ValueError(f"window_size={self.window_size} must be >= 1")
        if self.mlp_act not in ("silu", "gelu_tanh", "gelu_erf"):
            raise ValueError(
                f"mlp_act={self.mlp_act!r} (want 'silu', 'gelu_tanh' "
                "or 'gelu_erf')"
            )
        if self.window_pattern is not None:
            if self.window_size is None:
                raise ValueError(
                    "window_pattern needs window_size (which layers "
                    "would it alternate?)"
                )
            if self.window_pattern < 2:
                raise ValueError(
                    f"window_pattern={self.window_pattern} must be >= 2 "
                    "(1 means every layer — use plain window_size)"
                )
        if self.final_softcap is not None and self.fused_ce:
            raise ValueError(
                "final_softcap does not compose with fused_ce (the "
                "fused kernel never materialises the logits the cap "
                "transforms)"
            )
        if self.mlp_act != "silu" and self.n_experts:
            raise ValueError(
                "mlp_act applies to the dense FFN only; the expert "
                "path is SwiGLU"
            )

    # -- presets --------------------------------------------------------------
    @classmethod
    def tiny(cls, **kw):
        """For tests: fits an 8-device virtual CPU mesh comfortably."""
        d = dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            mlp_dim=128, rope_theta=10_000.0, remat=False,
        )
        d.update(kw)
        return cls(**d)

    @classmethod
    def tiny_moe(cls, **kw):
        """MoE variant of tiny: 4 experts, top-2, for mesh tests (ep<=4)."""
        d = dict(n_experts=4, moe_top_k=2, mlp_dim=64)
        d.update(kw)
        return cls.tiny(**d)

    @classmethod
    def small(cls, **kw):  # ~160M params
        d = dict(
            vocab_size=32_000, dim=768, n_layers=12, n_heads=12,
            n_kv_heads=4, mlp_dim=3072,
        )
        d.update(kw)
        return cls(**d)

    @classmethod
    def base_1b(cls, **kw):  # ~1.2B params
        d = dict(
            vocab_size=32_000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=4, mlp_dim=8192,
        )
        d.update(kw)
        return cls(**d)

    @classmethod
    def large_7b(cls, **kw):  # llama-2-7b-shaped
        d = dict(
            vocab_size=32_000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, mlp_dim=11008,
        )
        d.update(kw)
        return cls(**d)


def _block_specs(cfg: TransformerConfig):
    """Specs for ALL layers at once: leading ("layers",) stacked axis."""
    L = cfg.n_layers
    d, h, kv, hd, m = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.resolved_head_dim, cfg.mlp_dim,
    )
    # fan-in axis indices are relative to the *stacked* shapes below.
    proj = initializers.fan_in_normal(axis=1)
    specs = {
        "attn_norm": ParamSpec((L, d), ("layers", "embed"), initializers.zeros),
        "wq": ParamSpec(
            (L, d, h, hd), ("layers", "embed", "heads", "head_dim"), proj
        ),
        "wk": ParamSpec(
            (L, d, kv, hd), ("layers", "embed", "kv_heads", "head_dim"), proj
        ),
        "wv": ParamSpec(
            (L, d, kv, hd), ("layers", "embed", "kv_heads", "head_dim"), proj
        ),
        # wo fans in from (heads, head_dim): use stddev ~ 1/sqrt(h * hd).
        "wo": ParamSpec(
            (L, h, hd, d),
            ("layers", "heads", "head_dim", "embed"),
            initializers.truncated_normal(1.0 / (h * hd) ** 0.5),
        ),
        "mlp_norm": ParamSpec((L, d), ("layers", "embed"), initializers.zeros),
    }
    if cfg.qk_norm:
        # Per-head RMS gains over head_dim, shared across heads'
        # positions (Qwen3: one (head_dim,) gain per layer for q, one
        # for k).
        specs["q_norm"] = ParamSpec(
            (L, hd), ("layers", "head_dim"), initializers.zeros
        )
        specs["k_norm"] = ParamSpec(
            (L, hd), ("layers", "head_dim"), initializers.zeros
        )
    if cfg.post_norms:
        specs["post_attn_norm"] = ParamSpec(
            (L, d), ("layers", "embed"), initializers.zeros
        )
        specs["post_mlp_norm"] = ParamSpec(
            (L, d), ("layers", "embed"), initializers.zeros
        )
    if cfg.qkv_bias:
        specs["bq"] = ParamSpec(
            (L, h, hd), ("layers", "heads", "head_dim"), initializers.zeros
        )
        specs["bk"] = ParamSpec(
            (L, kv, hd), ("layers", "kv_heads", "head_dim"),
            initializers.zeros,
        )
        specs["bv"] = ParamSpec(
            (L, kv, hd), ("layers", "kv_heads", "head_dim"),
            initializers.zeros,
        )
    if cfg.n_experts:
        E = cfg.n_experts
        # Router output dim deliberately has no logical axis: the router is
        # tiny and its (b, s, E) logits feed a cross-expert top_k, so
        # sharding E there would only buy an all-gather.
        specs["router"] = ParamSpec(
            (L, d, E), ("layers", "embed", None), proj
        )
        eproj = initializers.fan_in_normal(axis=2)
        specs["w_gate"] = ParamSpec(
            (L, E, d, m), ("layers", "experts", "embed", "expert_mlp"), eproj
        )
        specs["w_up"] = ParamSpec(
            (L, E, d, m), ("layers", "experts", "embed", "expert_mlp"), eproj
        )
        specs["w_down"] = ParamSpec(
            (L, E, m, d),
            ("layers", "experts", "expert_mlp", "embed"),
            initializers.fan_in_normal(axis=2),
        )
    else:
        specs["w_gate"] = ParamSpec((L, d, m), ("layers", "embed", "mlp"), proj)
        specs["w_up"] = ParamSpec((L, d, m), ("layers", "embed", "mlp"), proj)
        specs["w_down"] = ParamSpec(
            (L, m, d),
            ("layers", "mlp", "embed"),
            initializers.fan_in_normal(axis=1),
        )
    return specs


@dataclasses.dataclass(frozen=True)
class Transformer(Module):
    cfg: TransformerConfig
    policy: Policy = Policy()

    # Quantized param trees (core.qtensor leaves) are consumed natively:
    # blocks dequantize per layer, the unembed at its matmul
    # (infer.quant.QuantizedModel passes the tree through untouched).
    supports_qtensors = True

    # ------------------------------------------------------------------ specs
    def specs(self):
        cfg = self.cfg
        s = {
            "embed": ParamSpec(
                (cfg.vocab_size, cfg.dim),
                ("vocab", "embed"),
                initializers.normal(1.0),
            ),
            "blocks": _block_specs(cfg),
            "final_norm": ParamSpec((cfg.dim,), ("embed",), initializers.zeros),
        }
        if not cfg.tie_embeddings:
            s["unembed"] = ParamSpec(
                (cfg.dim, cfg.vocab_size),
                ("embed", "vocab"),
                initializers.fan_in_normal(axis=0),
            )
        return s

    # ------------------------------------------------------------- one block
    def _layer_window(self, layer_idx):
        """This layer's effective sliding window: None (no window),
        the static config window, or — with ``window_pattern`` — a
        TRACED scalar that disables the window on non-pattern layers
        (a huge width; the mask comparisons it feeds broadcast traced
        values fine, which is what lets alternation ride the layer
        scan on the XLA/ring/decode paths). The flash kernel cannot
        consume a traced width — ``_self_attention`` branches between
        two static-window kernel calls there instead."""
        cfg = self.cfg
        if cfg.window_size is None:
            return None
        if cfg.window_pattern is None:
            return cfg.window_size
        if layer_idx is None:
            raise ValueError(
                "window_pattern needs a per-layer index; this call "
                "path (pipeline blocks_fn) does not thread one"
            )
        return jnp.where(
            layer_idx % cfg.window_pattern == 0,
            jnp.int32(cfg.window_size),
            jnp.int32(1 << 30),
        )

    @property
    def _attn_scale(self):
        cfg = self.cfg
        return (
            None if cfg.attn_scale is None else cfg.attn_scale ** -0.5
        )

    def _self_attention(self, q, k, v, *, segment_ids=None, layer_idx=None):
        """Causal self-attention over THIS call's q/k/v with the
        layer's effective window — the one dispatch point for every
        full-sequence attention in the model (training forward, dense
        prefill-from-empty, paged fresh prefill).

        With ``window_pattern`` + ``attn_impl="flash"`` the per-layer
        window cannot ride the scan as a traced scalar (the flash
        kernel prunes its KV grid — incl. the forced-window-grid
        ``window_block_k`` lever — from a STATIC window). Instead the
        layer index drives a ``lax.cond`` between two static-window
        kernel calls: the windowed branch compiles once on its pruned
        O(S*window) grid, the full branch once on the causal grid, and
        each scan step executes exactly one of them. XLA/ring keep the
        traced-scalar route (their masks broadcast traced widths
        fine).

        With ``cfg.tune_table`` the two branches ALSO resolve their
        kernel variants independently (windowed and full-causal are
        different shape classes), so a tuned alternating stack runs
        per-layer heterogeneous block shapes."""
        cfg = self.cfg
        if cfg.tune_table:
            from shifu_tpu.ops.pallas import registry as _preg

            _preg.use_table(cfg.tune_table)  # cached; warns+v0 on junk
        kw = dict(
            causal=True, segment_ids=segment_ids, impl=cfg.attn_impl,
            scale=self._attn_scale, softcap=cfg.attn_softcap,
        )
        if (
            cfg.window_pattern is not None
            and cfg.attn_impl == "flash"
            and layer_idx is not None
        ):
            return jax.lax.cond(
                layer_idx % cfg.window_pattern == 0,
                lambda q, k, v: dot_product_attention(
                    q, k, v, window=cfg.window_size, **kw
                ),
                lambda q, k, v: dot_product_attention(
                    q, k, v, window=None, **kw
                ),
                q, k, v,
            )
        return dot_product_attention(
            q, k, v, window=self._layer_window(layer_idx), **kw
        )

    def _block(
        self, p, h, sin, cos, segment_ids, cache_slice, cache_index,
        kv_mask=None, page_table=None, layer_idx=None, lora_slice=None,
    ):
        """One transformer block. ``p`` holds per-layer (unstacked) params.

        Returns (h, new_cache_slice, moe_aux); cache_slice is None outside
        decode; moe_aux is None for a dense FFN, else a dict of scalars.
        With ``page_table`` the cache_slice leaves are the FULL stacked
        paged pool (n_layers, n_pages, page_size, kv, hd) and
        ``layer_idx`` the (traced) layer to touch — the pool rides the
        layer scan as a carry and is only ever updated in place, page by
        page; materialising a per-layer slice would copy the entire
        layer every decode step — see :meth:`init_paged_cache`.

        ``lora_slice``: per-request serving adapters for THIS layer —
        ``(tables, row_ids)`` where tables maps a target weight name to
        {"a": (n_adapters, In, r), "b": (n_adapters, r, Out)} (flattened
        input/output dims, scale folded into b) and row_ids (b,) picks
        each row's adapter (0 = the all-zero no-adapter row). The delta
        ``x·A_i·B_i`` adds to the projection OUTPUT before bias/rope —
        exactly what merging W + scale·A·B into the weight would
        compute, but per row, so one batch serves many adapters.
        """
        cfg = self.cfg
        # Dequantize any quantized leaves HERE — per layer, at the
        # consumption point — so int8/fp8 stays the HBM format and the
        # convert+scale fuses into each matmul's operand read.
        p = dequantize_tree(p, h.dtype)

        def lora_delta(name, xin):
            """Per-row adapter delta (b, s, Out) for target ``name``,
            or None. xin: (b, s, In) — the flattened matmul input. The
            rank-r factors gather per ROW (adapters are small; the
            gather is b·In·r elements), so rows with different
            adapters ride one program."""
            if lora_slice is None:
                return None
            tabs, rows = lora_slice
            if name not in tabs:
                return None
            a = tabs[name]["a"][rows].astype(xin.dtype)  # (b, In, r)
            bm = tabs[name]["b"][rows].astype(xin.dtype)  # (b, r, Out)
            za = jnp.einsum("bsi,bir->bsr", xin, a)
            return jnp.einsum("bsr,bro->bso", za, bm)

        x = rms_norm(h, p["attn_norm"], eps=cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", x, p["wq"])
        k = jnp.einsum("bsd,dhk->bshk", x, p["wk"])
        v = jnp.einsum("bsd,dhk->bshk", x, p["wv"])
        dq = lora_delta("wq", x)
        if dq is not None:
            q = q + dq.reshape(q.shape)
        dk = lora_delta("wk", x)
        if dk is not None:
            k = k + dk.reshape(k.shape)
        dv = lora_delta("wv", x)
        if dv is not None:
            v = v + dv.reshape(v.shape)
        if cfg.qkv_bias:
            q = q + p["bq"]
            k = k + p["bk"]
            v = v + p["bv"]
        if cfg.qk_norm:
            # Per-head RMS over head_dim BEFORE rope (Qwen3 order).
            q = rms_norm(q, p["q_norm"], eps=cfg.norm_eps)
            k = rms_norm(k, p["k_norm"], eps=cfg.norm_eps)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)

        if cache_slice is None:
            attn = self._self_attention(
                q, k, v, segment_ids=segment_ids, layer_idx=layer_idx
            )
            # Named for the selective remat policies ("flash" /
            # "dots_flash"): saving this one (b, s, h, hd) tensor per
            # layer spares the backward pass a full re-run of the
            # attention forward — the block's only non-matmul
            # FLOPs-heavy op — at ~2 bytes/position of extra HBM.
            attn = _checkpoint_name(attn, "attn_out")
            new_cache = None
        elif page_table is not None:
            attn, new_cache = self._paged_block_attention(
                q, k, v, cache_slice, cache_index, page_table, kv_mask,
                layer_idx,
            )
        else:
            if getattr(cache_index, "ndim", 0) == 1:
                # Per-row write offsets (continuous batching: every slot
                # decodes at its own length). q_len > 1 scatters each
                # row's chunk at its own offset (batched speculative
                # verify: K+1 positions per row).
                b, q_len_w = k.shape[:2]
                rows = jnp.arange(b)
                if q_len_w == 1:
                    ck = (
                        cache_slice["k"]
                        .at[rows, cache_index]
                        .set(k[:, 0].astype(cache_slice["k"].dtype))
                    )
                    cv = (
                        cache_slice["v"]
                        .at[rows, cache_index]
                        .set(v[:, 0].astype(cache_slice["v"].dtype))
                    )
                else:
                    cols = cache_index[:, None] + jnp.arange(q_len_w)[None]
                    ck = (
                        cache_slice["k"]
                        .at[rows[:, None], cols]
                        .set(k.astype(cache_slice["k"].dtype))
                    )
                    cv = (
                        cache_slice["v"]
                        .at[rows[:, None], cols]
                        .set(v.astype(cache_slice["v"].dtype))
                    )
            else:
                ck = jax.lax.dynamic_update_slice(
                    cache_slice["k"], k.astype(cache_slice["k"].dtype),
                    (0, cache_index, 0, 0),
                )
                cv = jax.lax.dynamic_update_slice(
                    cache_slice["v"], v.astype(cache_slice["v"].dtype),
                    (0, cache_index, 0, 0),
                )
            if (
                q.shape[1] > 1
                and kv_mask is None
                and type(cache_index) is int
                and cache_index == 0
            ):
                # Prefill from an empty cache: the only valid keys are this
                # call's own k/v, so attend locally through the real
                # attention dispatch (flash kernel for long prompts) rather
                # than scoring against the whole preallocated cache. Only
                # valid without kv_mask — i.e. right-padded prompts, where
                # causality already hides the tail from every real query;
                # with a mask (left-padding/holes) fall through to the
                # masked cache path below.
                attn = self._self_attention(q, k, v, layer_idx=layer_idx)
            else:
                # Single-token decode (or chunked prefill at a traced
                # offset): score against the cache. Positions > index hold
                # zeros-from-init; causal mask with end-alignment cannot be
                # used because the cache is longer than (index + q_len), so
                # the mask is built in slot space with a query offset.
                attn = _decode_attention(
                    q, ck, cv, cache_index, cfg.attn_impl, kv_mask=kv_mask,
                    window=self._layer_window(layer_idx),
                    scale=self._attn_scale, softcap=cfg.attn_softcap,
                )
            new_cache = {"k": ck, "v": cv}

        o = jnp.einsum("bshk,hkd->bsd", attn, p["wo"])
        do = lora_delta("wo", attn.reshape(*attn.shape[:2], -1))
        if do is not None:
            o = o + do
        if cfg.post_norms:
            # Sandwich norm (Gemma-2): normalise the attention OUTPUT
            # before its residual add.
            o = rms_norm(o, p["post_attn_norm"], eps=cfg.norm_eps)
        h = h + o

        x = rms_norm(h, p["mlp_norm"], eps=cfg.norm_eps)
        if cfg.n_experts:
            if lora_slice is not None and (
                set(lora_slice[0]) & {"w_gate", "w_up", "w_down"}
            ):
                # Guard at the seam where the drop would happen: the
                # expert dispatch/combine path has no per-row delta
                # hook, so FFN adapter tables here would be silently
                # ignored. (The serving engine refuses this combination
                # earlier with a friendlier message.)
                raise NotImplementedError(
                    "FFN lora targets on an MoE config are not applied "
                    "by the expert path"
                )
            down, moe_aux = self._moe_ffn(p, x)
        else:
            gate = jnp.einsum("bsd,dm->bsm", x, p["w_gate"])
            up = jnp.einsum("bsd,dm->bsm", x, p["w_up"])
            for name in ("w_gate", "w_up"):
                d = lora_delta(name, x)
                if d is not None:
                    if name == "w_gate":
                        gate = gate + d
                    else:
                        up = up + d
            act = {
                "silu": jax.nn.silu,
                "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
                "gelu_erf": lambda x: jax.nn.gelu(x, approximate=False),
            }[cfg.mlp_act](gate) * up
            down = jnp.einsum("bsm,md->bsd", act, p["w_down"])
            dd = lora_delta("w_down", act)
            if dd is not None:
                down = down + dd
            moe_aux = None
        if cfg.post_norms:
            down = rms_norm(down, p["post_mlp_norm"], eps=cfg.norm_eps)
        h = h + down
        h = constrain(h, ("batch", "seq", "act_embed"))
        return h, new_cache, moe_aux

    def _paged_kernel_ok(self) -> bool:
        """Whether the Pallas paged-decode kernel may serve this
        config's decode/verify steps. Beyond the mesh condition
        (_pallas_paged_ok), the kernel applies ONE static window to
        every layer and no logit capping — an alternating-window or
        softcapped stack (Gemma-2) must take the XLA gather fallback,
        which handles the traced per-layer window and the tanh cap
        exactly (decode is memory-bound; the flash win lives in the
        prefill/training kernels, which DO support both)."""
        cfg = self.cfg
        return (
            cfg.attn_impl == "flash"
            and cfg.attn_softcap is None
            and cfg.window_pattern is None
            and _pallas_paged_ok()
        )

    # ------------------------------------------------------------ paged kv
    def _paged_block_attention(
        self, q, k, v, pool, cache_index, page_table, kv_mask, layer_idx
    ):
        """Attention over the PAGED kv pool (full stack, one layer live).

        pool: {"k","v"} of (n_layers, n_pages, page_size, kv, hd) —
        physical pages shared by all rows; ``layer_idx`` (traced int32)
        selects the layer this block touches. The pool is a scan CARRY:
        all writes are in-place page scatters and (on the Pallas path)
        all reads are per-page DMAs, so the multi-GB pool is never
        sliced or restacked per layer. page_table: (b, pages_per_row)
        int32 mapping row-logical page j to a physical page (unallocated
        entries point at the scratch page 0; kv_mask hides whatever
        lands there). Logical position t of row b lives at
        pool[layer, table[b, t // ps], t % ps].

        Four call shapes, mirroring the dense path:
          * prefill (q_len > 1, cache_index == 0, the static int): k/v
            for the whole bucket scatter to this row's pages in one
            batched write (q_len % page_size == 0 enforced by the
            engine's buckets); attention runs locally over the fresh
            k/v (right-padding is hidden by causality, exactly the
            dense fast path).
          * SUFFIX prefill (q_len > 1, cache_index a traced scalar —
            the page-aligned offset where the suffix starts): writes
            land in the pages at offset//ps onward, attention runs
            over the row's gathered pages with slot-space causality —
            queries see the already-cached prefix. This is what prefix
            caching prefills after a page-table hit.
          * decode (q_len == 1, cache_index a (b,) vector): one-token
            scatter at (table[b, t//ps], t%ps), then attention over the
            row's gathered pages with the same slot-space masking as the
            dense cache (_decode_attention).
          * BATCH CHUNK (q_len > 1, cache_index a (b,) vector): every
            row writes q_len consecutive tokens starting at its own
            offset — positions freely cross page boundaries (per-token
            (phys, off) scatter indices) — then attends over its
            gathered pages with slot-space causality (queries at
            n..n+q_len-1). This is the speculative-verify shape: K+1
            positions for one memory-bound pass.
        """
        b, q_len, _, _ = q.shape
        _, n_pages, ps, n_kv, hd = pool["k"].shape
        pages_per_row = page_table.shape[1]
        li = layer_idx
        # Quantized pool (init_paged_cache(dtype=int8)): writes quantize
        # at the scatter (int8 data + per-(pos, kv) f32 scale), reads
        # dequantize — inside the Pallas kernel on the decode fast path,
        # at the gather on the XLA fallback/suffix paths. Scales stay in
        # pool layout and are gathered per layer at the read: an
        # all-layer pre-gather into slot-logical layout (page-major
        # scale pool + scan xs + per-write logical mirror) was built and
        # MEASURED SLOWER on v5e at the production page-256 grain
        # (8.3 vs 6.8 ms/step at the bench mix — the one-shot gather's
        # transpose and the in-scan mirror scatters both materialise
        # badly, while 160 contiguous 8KB slices per layer gather fine).
        quantized = "k_scale" in pool
        if quantized:
            from shifu_tpu.core.qtensor import dequantize_kv, quantize_kv

            kc, vc = k, v  # quantize_kv converts at each write below
        else:
            kc = k.astype(pool["k"].dtype)
            vc = v.astype(pool["v"].dtype)
        csk = pool.get("k_scale")
        csv = pool.get("v_scale")

        if q_len > 1 and getattr(cache_index, "ndim", 0) == 1:
            # BATCH CHUNK: per-row multi-token scatter + slot-space
            # attention (docstring). No page-alignment requirement —
            # per-token scatter indices cross page boundaries freely.
            pos = cache_index[:, None] + jnp.arange(q_len)[None, :]
            rows = jnp.arange(b)[:, None]
            # Positions past the row's logical capacity go to SCRATCH
            # page 0 (never read), not to a clamped table column: XLA
            # clamps out-of-bounds gather indices, and the last column
            # holds the row's real last page — a speculative verifier
            # writing its full k+1-wide chunk near max_len would
            # otherwise overwrite real cached K/V that this same pass
            # then attends over.
            in_range = pos < pages_per_row * ps
            phys = jnp.where(
                in_range,
                page_table[
                    rows, jnp.minimum(pos // ps, pages_per_row - 1)
                ],
                0,
            )  # (b, q_len)
            off = pos % ps
            kw_, vw_ = kc, vc
            if quantized:
                kw_, ksw_ = quantize_kv(kw_, scale_dtype=csk.dtype)
                vw_, vsw_ = quantize_kv(vw_, scale_dtype=csv.dtype)
                csk = csk.at[li, phys, off].set(ksw_)
                csv = csv.at[li, phys, off].set(vsw_)
            ck = pool["k"].at[li, phys, off].set(kw_)
            cv = pool["v"].at[li, phys, off].set(vw_)
            if self._paged_kernel_ok():
                # Multi-query paged kernel: the whole chunk scores in
                # ONE pass over the pool (queries fold into the row
                # axis) — the (b, pages_per_row * ps, kv, hd) gathered
                # copy never exists. This is the speculative-verify
                # hot path: verify traffic drops from ~3x the pool
                # bytes (gather write + read + pool read) to the pool
                # read itself.
                from shifu_tpu.ops.pallas.paged_attention import (
                    paged_decode_attention,
                )

                attn = paged_decode_attention(
                    q, ck, cv, page_table, cache_index, layer=li,
                    window=self.cfg.window_size, kv_mask=kv_mask,
                    scale=self._attn_scale,
                    k_scale=csk if quantized else None,
                    v_scale=csv if quantized else None,
                    int8_qk=quantized and self.cfg.int8_qk_dot,
                )
            else:
                gk = ck[li, page_table]
                gv = cv[li, page_table]
                if quantized:
                    gk = dequantize_kv(gk, csk[li, page_table], q.dtype)
                    gv = dequantize_kv(gv, csv[li, page_table], q.dtype)
                gk = gk.reshape(b, pages_per_row * ps, n_kv, hd)
                gv = gv.reshape(b, pages_per_row * ps, n_kv, hd)
                attn = _decode_attention(
                    q, gk, gv, cache_index, self.cfg.attn_impl,
                    kv_mask=kv_mask, window=self._layer_window(li),
                    scale=self._attn_scale,
                    softcap=self.cfg.attn_softcap,
                )
            new_pool = {"k": ck, "v": cv}
            if quantized:
                new_pool["k_scale"] = csk
                new_pool["v_scale"] = csv
            return attn, new_pool

        if q_len > 1:
            if q_len % ps:
                raise ValueError(
                    f"paged prefill length {q_len} must be a multiple of "
                    f"the page size {ps}"
                )
            if b != 1:
                raise ValueError(
                    "paged prefill is per-request (batch 1); batch decode "
                    "is where rows share the pool"
                )
            if kv_mask is not None:
                raise ValueError(
                    "paged prefill attends via causality over real "
                    "positions; kv_mask would be silently ignored"
                )
            kv_block = kc[0].reshape(q_len // ps, ps, n_kv, hd)
            v_block = vc[0].reshape(q_len // ps, ps, n_kv, hd)
            if quantized:
                kv_block, ks_block = quantize_kv(
                    kv_block, scale_dtype=csk.dtype
                )
                v_block, vs_block = quantize_kv(
                    v_block, scale_dtype=csv.dtype
                )
            if type(cache_index) is int and cache_index == 0:
                # Fresh prefill: local attention fast path (flash for
                # long prompts), nothing cached to look at.
                phys = page_table[0, : q_len // ps]  # (np_b,)
                ck = pool["k"].at[li, phys].set(kv_block)
                cv = pool["v"].at[li, phys].set(v_block)
                if quantized:
                    csk = csk.at[li, phys].set(ks_block)
                    csv = csv.at[li, phys].set(vs_block)
                attn = self._self_attention(q, k, v, layer_idx=li)
            else:
                # Page-aligned suffix prefill at a traced offset: the
                # caller guarantees cache_index % ps == 0 and that the
                # pages below the offset hold the shared prefix.
                start = cache_index // ps
                phys = jax.lax.dynamic_slice_in_dim(
                    page_table[0], start, q_len // ps
                )
                ck = pool["k"].at[li, phys].set(kv_block)
                cv = pool["v"].at[li, phys].set(v_block)
                if quantized:
                    csk = csk.at[li, phys].set(ks_block)
                    csv = csv.at[li, phys].set(vs_block)
                # One mixed-index gather: the scalar layer index rides the
                # gather instead of materialising the full layer slice.
                gk = ck[li, page_table]
                gv = cv[li, page_table]
                if quantized:
                    gk = dequantize_kv(gk, csk[li, page_table], k.dtype)
                    gv = dequantize_kv(gv, csv[li, page_table], v.dtype)
                gk = gk.reshape(b, page_table.shape[1] * ps, n_kv, hd)
                gv = gv.reshape(b, page_table.shape[1] * ps, n_kv, hd)
                attn = _decode_attention(
                    q, gk, gv, cache_index, self.cfg.attn_impl,
                    window=self._layer_window(li),
                    scale=self._attn_scale,
                    softcap=self.cfg.attn_softcap,
                )
        else:
            if getattr(cache_index, "ndim", 0) != 1:
                raise ValueError(
                    "paged decode needs per-row cache_index (continuous "
                    "batching is the point of a paged pool)"
                )
            rows = jnp.arange(b)
            phys = page_table[rows, cache_index // ps]  # (b,)
            off = cache_index % ps
            kw, vw = kc[:, 0], vc[:, 0]
            if quantized:
                kw, ksw = quantize_kv(kw, scale_dtype=csk.dtype)
                vw, vsw = quantize_kv(vw, scale_dtype=csv.dtype)
            # Inactive slots all point at scratch page 0 — duplicate
            # scatter indices there are benign (nothing reads scratch).
            ck = pool["k"].at[li, phys, off].set(kw)
            cv = pool["v"].at[li, phys, off].set(vw)
            if quantized:
                csk = csk.at[li, phys, off].set(ksw)
                csv = csv.at[li, phys, off].set(vsw)
            if self._paged_kernel_ok():
                # Pallas paged-decode kernel: reads each live page once,
                # straight from the stacked pool via the scalar-prefetched
                # page table and layer index — neither the per-layer
                # slice nor the (b, pages_per_row * ps, kv, hd) gather
                # ever exists (ops/pallas/paged_attention.py). An int8
                # pool dequantizes INSIDE the kernel (per-lane scales).
                from shifu_tpu.ops.pallas.paged_attention import (
                    paged_decode_attention,
                )

                attn = paged_decode_attention(
                    q[:, 0], ck, cv, page_table, cache_index, layer=li,
                    window=self.cfg.window_size, kv_mask=kv_mask,
                    scale=self._attn_scale,
                    k_scale=csk if quantized else None,
                    v_scale=csv if quantized else None,
                    int8_qk=quantized and self.cfg.int8_qk_dot,
                )[:, None]
            else:
                # Gather each row's pages into its logical view with ONE
                # mixed-index gather (scalar layer + page indices): the
                # layer slice itself is never materialised. Traffic is
                # the gathered copy's write+read — the kernel path above
                # avoids even that.
                gk = ck[li, page_table]
                gv = cv[li, page_table]
                if quantized:
                    gk = dequantize_kv(gk, csk[li, page_table], q.dtype)
                    gv = dequantize_kv(gv, csv[li, page_table], q.dtype)
                gk = gk.reshape(b, pages_per_row * ps, n_kv, hd)
                gv = gv.reshape(b, pages_per_row * ps, n_kv, hd)
                attn = _decode_attention(
                    q, gk, gv, cache_index, self.cfg.attn_impl,
                    kv_mask=kv_mask, window=self._layer_window(li),
                    scale=self._attn_scale,
                    softcap=self.cfg.attn_softcap,
                )
        new_pool = {"k": ck, "v": cv}
        if quantized:
            new_pool["k_scale"] = csk
            new_pool["v_scale"] = csv
        return attn, new_pool

    # ------------------------------------------------------------- moe ffn
    def _moe_ffn(self, p, x):
        """Expert-parallel SwiGLU FFN: grouped dispatch by default, the
        dense dispatch/combine-einsum oracle under
        ``moe_impl="einsum"``. Both build the same (E, b, C, d) expert
        buffers (identical grouped expert matmuls and ep-sharding
        pattern); they differ only in how tokens move in and out —
        see ops.moe module docstring.

        The default ("grouped") additionally consults the kernel
        variant registry: an active tune table may route THIS shape
        class (seq bucket, dim, experts, top_k, dtype) to the einsum
        formulation where it measured faster (tiny E·C — the two are
        bit-identical routings, so the swap is numerics-free).
        Explicit ``moe_impl="einsum"`` stays an unconditional oracle
        switch for parity tests and the bench sub-leg."""
        impl = self.cfg.moe_impl
        if impl == "grouped":
            from shifu_tpu.ops.pallas import registry as _preg

            if self.cfg.tune_table:
                _preg.use_table(self.cfg.tune_table)
            variant = _preg.resolve(_preg.ShapeClass.moe(
                seq_len=x.shape[1], dim=x.shape[2],
                experts=self.cfg.n_experts, top_k=self.cfg.moe_top_k,
                dtype=x.dtype,
            ))
            impl = str(variant.p.get("impl", "grouped"))
        if impl == "einsum":
            return self._moe_ffn_einsum(p, x)
        return self._moe_ffn_grouped(p, x)

    def _expert_mlps(self, p, xe):
        """The grouped expert SwiGLU matmuls over (E, b, C, d) buffers —
        shared verbatim by both dispatch implementations (the parity
        tests compare everything AROUND this)."""
        xe = constrain(xe, ("act_experts", "batch", None, "act_embed"))
        gate = jnp.einsum("ebcd,edm->ebcm", xe, p["w_gate"])
        up = jnp.einsum("ebcd,edm->ebcm", xe, p["w_up"])
        dn = jnp.einsum("ebcm,emd->ebcd", jax.nn.silu(gate) * up, p["w_down"])
        return constrain(dn, ("act_experts", "batch", None, "act_embed"))

    def _moe_ffn_einsum(self, p, x):
        """Dense dispatch/combine einsums (GShard form) — the
        correctness oracle. O(b·s·E·C·d) MACs of data movement per
        contraction on top of the expert FFN flops."""
        cfg = self.cfg
        b, s, d = x.shape
        cap = moe_capacity(s, cfg.moe_top_k, cfg.n_experts, cfg.moe_capacity_factor)
        logits = jnp.einsum("bsd,de->bse", x, p["router"])
        dispatch, combine, aux = route_top_k(logits, cfg.moe_top_k, cap)

        # (E, b, C, d) expert input buffers — E leads so one constraint pins
        # the ep sharding for the whole expert-compute segment.
        xe = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(x.dtype), x)
        dn = self._expert_mlps(p, xe)
        # Combine in f32 (gate weights are f32), cast back to the residual
        # stream dtype.
        out = jnp.einsum(
            "bsec,ebcd->bsd", combine, dn.astype(jnp.float32)
        ).astype(x.dtype)
        return out, aux

    def _moe_ffn_grouped(self, p, x):
        """Sorted/grouped dispatch (the default fast path).

        The routing op returns each assignment's (expert, slot) cell;
        this method materialises the INVERSE permutation — for every
        buffer cell, which token (if any) fills it — as one static
        int32 scatter, builds the (E, b, C, d) expert buffers with one
        gather (so the dense one-hot dispatch einsum never exists),
        runs the identical grouped expert matmuls, and combines by
        gathering each assignment's expert output back through the
        forward permutation with its gate weight. Dispatch/combine
        traffic is O((E·C + s·k)·d) ELEMENTS, not O(b·s·E·C·d) MACs.

        Fixed shapes throughout (scatter/gather sizes depend only on
        (b, s, E, C, k)), so it jits once; the ep-sharding constraint
        sits on the same (E, b, C, d) buffers as the einsum path, so
        XLA inserts the identical token↔expert all-to-all under a mesh.
        Dropped assignments route to a sentinel overflow cell that is
        sliced off (dispatch) or weight-masked to zero (combine) —
        exactly the einsum path's zero-weight drop semantics.
        """
        cfg = self.cfg
        b, s, d = x.shape
        k = cfg.moe_top_k
        E = cfg.n_experts
        cap = moe_capacity(s, k, E, cfg.moe_capacity_factor)
        logits = jnp.einsum("bsd,de->bse", x, p["router"])
        e_idx, slot, w, keep, aux = route_top_k_grouped(logits, k, cap)

        # Flatten assignments (token-major: assignment a ↔ token a // k).
        n_a = s * k
        e_f = e_idx.reshape(b, n_a)
        slot_f = slot.reshape(b, n_a)
        keep_f = keep.reshape(b, n_a)
        # Combined buffer cell id; dropped assignments go to the E*cap
        # overflow cell (written then sliced off below).
        cell = jnp.where(keep_f, e_f * cap + slot_f, E * cap)
        rows = jnp.arange(b)[:, None]

        # Inverse permutation: cell -> flat assignment index (sentinel
        # n_a = empty). Kept cells are unique by the cumsum slot
        # construction; only the overflow cell takes collisions.
        inv = (
            jnp.full((b, E * cap + 1), n_a, jnp.int32)
            .at[rows, cell]
            .set(jnp.broadcast_to(jnp.arange(n_a, dtype=jnp.int32), (b, n_a)))
        )[:, : E * cap]

        # Dispatch: gather token rows into the expert buffers. Row s of
        # the padded stream is zero, so empty cells hold exact zeros —
        # bit-identical to the one-hot einsum's untouched cells.
        x_pad = jnp.concatenate(
            [x, jnp.zeros((b, 1, d), x.dtype)], axis=1
        )
        tok = jnp.where(inv < n_a, inv // k, s)  # (b, E*cap)
        xe = jnp.take_along_axis(x_pad, tok[..., None], axis=1)
        xe = xe.reshape(b, E, cap, d).transpose(1, 0, 2, 3)  # (E, b, C, d)

        dn = self._expert_mlps(p, xe)

        # Combine: gather each assignment's expert output through the
        # forward permutation; weight-sum the k choices per token in
        # f32 (gate weights are f32 — matches the einsum combine).
        dn_f = (
            dn.transpose(1, 0, 2, 3)
            .reshape(b, E * cap, d)
            .astype(jnp.float32)
        )
        cell_c = jnp.minimum(cell, E * cap - 1)  # clamp drops (weight 0)
        y = jnp.take_along_axis(dn_f, cell_c[..., None], axis=1)
        wgt = jnp.where(keep_f, w.reshape(b, n_a), 0.0)
        out = (
            (y * wgt[..., None]).reshape(b, s, k, d).sum(axis=2)
        ).astype(x.dtype)
        return out, aux

    # ---------------------------------------------------------------- forward
    def __call__(
        self,
        params,
        tokens,
        *,
        positions=None,
        segment_ids=None,
        cache=None,
        cache_index=None,
        kv_mask=None,
        page_table=None,
        logits_at=None,
        return_aux=False,
        return_hidden=False,
        blocks_fn=None,
        rope_regime_len=None,
        lora=None,
    ):
        """Compute logits.

        Args:
          params: parameter pytree from ``self.init``.
          tokens: (batch, seq) int32.
          positions: optional (batch, seq) or (seq,) positions for RoPE;
            defaults to arange(seq) (+ cache_index in decode).
          segment_ids: optional (batch, seq) packing segments.
          cache: optional KV cache pytree from ``self.init_cache`` (decode).
          cache_index: int32 scalar — write offset into the cache.
          kv_mask: optional (batch, max_seq_len) bool — cache slots a query
            may attend (on top of slot-space causality). Used by the
            generation stack to hide right-padding written during prefill
            of ragged prompts. Decode path only.
          page_table: optional (batch, pages_per_row) int32 — the cache is
            a PAGED pool from ``init_paged_cache`` and this maps each
            row's logical pages onto physical ones (_paged_block_attention
            docstring). Requires ``cache``.
          logits_at: optional (batch,) int32 — compute logits only at this
            one position per row. Skips the (batch, seq, vocab) unembed on
            prefill, where just the last real token's logits feed the
            sampler; returned logits are (batch, 1, vocab).
          return_aux: also return the MoE aux-loss dict (mean over layers of
            {"lb", "rz", "dropped"}; None for a dense model). Training-path
            only — unsupported together with ``cache``.
          return_hidden: return the post-final-norm hidden states
            (b, s, d) INSTEAD of logits, skipping the unembed — the
            fused-CE loss consumes these so the (b, s, vocab) logits
            never materialise. Training path only (no cache).
          lora: optional per-request serving adapters ``(tables,
            row_ids)``: tables map target weight names to
            {"a": (L, n_adapters, In, r), "b": (L, n_adapters, r, Out)}
            stacked factors (layer axis leading — they ride the block
            scan beside the layer params) and row_ids (b,) int32 picks
            each row's adapter, 0 = none. See ``_block.lora_delta``;
            the serving engines build these (infer.engine
            ``lora=LoraServingConfig(...)``). Unsupported with
            ``blocks_fn`` (the pipeline schedules own the scan).
          blocks_fn: optional override for the block-stack execution:
            ``(stacked_block_params, h, sin, cos, segment_ids) -> h``, or
            ``-> (h, moe_aux)`` for an MoE config (aux = pytree of f32
            scalars, already averaged over layers). The pipeline engine
            (parallel.pipeline) injects its schedule here so embed/rope/
            norm/unembed/loss stay this method's single implementation.
            Training path only (no cache).

        Returns:
          (logits, new_cache) if cache is not None else logits; with
          ``return_aux``, (logits, moe_aux).
          logits: (batch, seq, vocab) in the policy's output dtype.
        """
        cfg = self.cfg
        if cache is not None and segment_ids is not None:
            raise ValueError(
                "segment_ids with a KV cache is not supported: the decode "
                "path has no packed-segment masking, and silently ignoring "
                "packing would leak attention across sequences"
            )
        if cache is None and kv_mask is not None:
            raise ValueError(
                "kv_mask is a decode-path (cache) concept — cache slots a "
                "query may attend. On the no-cache forward it would be "
                "silently ignored; mask padding there via segment_ids or a "
                "loss mask instead"
            )
        if page_table is not None and cache is None:
            raise ValueError(
                "page_table maps a paged cache pool; pass the pool from "
                "init_paged_cache as cache="
            )
        p = self.policy.cast_to_compute(params)
        b, s = tokens.shape

        # Embedding lookup. The table's embed axis is fsdp-sharded at rest,
        # but the gather OUTPUT wants (batch->fsdp, seq->sp): if the gather
        # inherits operand-passthrough sharding, SPMD must replicate-then-
        # repartition the (b, s, d) output EVERY microbatch ("involuntary
        # full rematerialization"). Un-shard the table's embed axis first:
        # that all-gather is loop-invariant, so XLA hoists it out of the
        # microbatch scan, and the gather is born index-passthrough sharded.
        # Training path only — on the decode path (cache) there is no scan
        # to hoist out of, and forcing a per-step table all-gather over
        # fsdp would cost far more than the row gather it replaces.
        w_embed = (
            constrain(p["embed"], ("vocab", None)) if cache is None
            else p["embed"]
        )
        h = jnp.take(w_embed, tokens, axis=0)
        if cfg.embed_scale:
            # Gemma convention: normalizer computed in the activation
            # dtype (HF casts the sqrt(dim) tensor to hidden dtype).
            h = h * jnp.asarray(cfg.dim, h.dtype) ** jnp.asarray(
                0.5, h.dtype
            )
        h = constrain(h, ("batch", "seq", "act_embed"))

        if positions is None:
            positions = jnp.arange(s)
            if cache_index is not None:
                if getattr(cache_index, "ndim", 0) == 1:
                    positions = positions[None, :] + cache_index[:, None]
                else:
                    positions = positions + cache_index
        # rope_regime_len: the sequence length the length-sensitive rope
        # scalings key off, when the caller knows better than this
        # call's positions — a chunked prefill's chunks must all bake
        # the FINAL prompt length's frequencies (ops/rope.py).
        sin, cos = rope_frequencies(
            cfg.resolved_head_dim, positions, theta=cfg.rope_theta,
            scaling=cfg.rope_scaling, regime_len=rope_regime_len,
        )

        block = self._block
        if cfg.remat and cache is None:
            cp = jax.checkpoint_policies
            policy = {
                "dots": cp.dots_with_no_batch_dims_saveable,
                "full": None,
                "flash": cp.save_only_these_names("attn_out"),
                "dots_flash": cp.save_from_both_policies(
                    cp.dots_with_no_batch_dims_saveable,
                    cp.save_only_these_names("attn_out"),
                ),
            }[cfg.remat_policy]
            block = jax.checkpoint(block, static_argnums=(), policy=policy)

        if lora is not None and blocks_fn is not None:
            raise ValueError(
                "lora adapters do not compose with blocks_fn (the "
                "pipeline schedules restructure the block scan)"
            )
        lora_tabs, lora_rows = lora if lora is not None else (None, None)

        if cache is None:
            if blocks_fn is not None:
                out = blocks_fn(p["blocks"], h, sin, cos, segment_ids)
                # MoE overrides return (h, aux-scalars); tree_map(mean)
                # below is then an identity on already-averaged scalars.
                if cfg.n_experts:
                    if not (isinstance(out, tuple) and len(out) == 2):
                        # A bare array would tuple-unpack along its
                        # leading axis into garbage h/aux — fail fast.
                        raise TypeError(
                            "blocks_fn must return (h, moe_aux) for an "
                            f"MoE config, got {type(out).__name__}"
                        )
                    h, auxes = out
                else:
                    h, auxes = out, None
            else:
                def body(carry, xs):
                    layer_p, li, tab = xs
                    out, _, aux = block(
                        layer_p, carry, sin, cos, segment_ids, None,
                        None, layer_idx=li, lora_slice=(
                            (tab, lora_rows) if tab is not None else None
                        ),
                    )
                    return out, aux

                h, auxes = jax.lax.scan(
                    body, h,
                    (p["blocks"], jnp.arange(cfg.n_layers), lora_tabs),
                )
            new_cache = None
        else:
            if return_aux:
                raise ValueError("return_aux is a training-path (no-cache) flag")

            if page_table is not None:
                # Paged pool: the multi-GB pool rides the scan as a CARRY
                # updated in place (page scatters + per-page kernel reads
                # addressed by the layer index). Passing it as scan xs/ys
                # would dynamic-slice AND restack one full layer per
                # block — reading and writing the entire pool every
                # decode step. (An unrolled python loop over layers was
                # tried here on the hypothesis that scan's dynamic
                # param slices copy each layer's weights before the
                # matmuls read them — measured NEUTRAL-to-slightly-
                # worse at 1.2B/b16 on v5e, so scan's slices evidently
                # read in place and the scan stays.)
                def body(carry, xs):
                    hh, pool = carry
                    layer_p, li, tab = xs
                    out, pool, aux = block(
                        layer_p, hh, sin, cos, None, pool, cache_index,
                        kv_mask, page_table, li, lora_slice=(
                            (tab, lora_rows) if tab is not None else None
                        ),
                    )
                    return (out, pool), aux

                (h, new_cache), auxes = jax.lax.scan(
                    body, (h, cache),
                    (p["blocks"], jnp.arange(cfg.n_layers), lora_tabs),
                )
            else:
                def body(carry, xs):
                    layer_p, cache_slice, li, tab = xs
                    out, new_slice, aux = block(
                        layer_p, carry, sin, cos, None, cache_slice,
                        cache_index, kv_mask, page_table,
                        layer_idx=li, lora_slice=(
                            (tab, lora_rows) if tab is not None else None
                        ),
                    )
                    return out, (new_slice, aux)

                h, (new_cache, auxes) = jax.lax.scan(
                    body, h,
                    (p["blocks"], cache, jnp.arange(cfg.n_layers),
                     lora_tabs),
                )

        h = rms_norm(h, p["final_norm"], eps=cfg.norm_eps)
        moe_aux = (
            jax.tree_util.tree_map(jnp.mean, auxes)
            if (return_aux or return_hidden) and cfg.n_experts
            else None
        )
        if return_hidden:
            if cache is not None:
                raise ValueError("return_hidden is a training-path flag")
            if logits_at is not None:
                raise ValueError(
                    "logits_at selects positions of the LOGITS; with "
                    "return_hidden it would be silently ignored — slice "
                    "the returned hidden states instead"
                )
            return (h, moe_aux) if return_aux else h
        if logits_at is not None:
            h = jnp.take_along_axis(h, logits_at[:, None, None], axis=1)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", h, p["embed"])
        else:
            w_un = dequantize_tree(p["unembed"], h.dtype)
            logits = jnp.einsum("bsd,dv->bsv", h, w_un)
        if cfg.final_softcap is not None:
            # Gemma-2 final logit soft-capping, tanh in f32 (bf16 tanh
            # near the cap loses the top-1 ordering the cap preserves).
            c = jnp.float32(cfg.final_softcap)
            logits = (
                jnp.tanh(logits.astype(jnp.float32) / c) * c
            ).astype(logits.dtype)
        logits = constrain(logits, ("batch", "seq", "act_vocab"))
        logits = self.policy.cast_to_output(logits)
        if return_aux:
            return logits, moe_aux
        return logits if cache is None else (logits, new_cache)

    # ------------------------------------------------------------------- loss
    def loss(self, params, batch, *, blocks_fn=None, fused_ce=None):
        """Next-token loss. batch: {"tokens": (b, s), optional "mask",
        "segment_ids", "positions"}. Predicts tokens[:, 1:].

        ``fused_ce`` (default: the config's ``fused_ce`` flag): fuse the
        unembed matmul into a sequence-chunked, rematerialised
        cross-entropy so the (b, s, vocab) logits — the largest tensor
        of a training step — never materialise in HBM
        (ops.losses.fused_softmax_cross_entropy). A MEMORY feature: the
        backward recomputes the unembed, costing ~4% throughput at
        b8 x s2048 x v32k on v5e — enable it when the logits tensor is
        what forces a smaller batch/model (large vocab, long seq).
        """
        cfg = self.cfg
        if fused_ce is None:
            fused_ce = cfg.fused_ce
        if fused_ce and cfg.final_softcap is not None:
            # Config validation catches cfg.fused_ce; the per-call
            # override must not silently skip the Gemma-2 logit cap
            # (the fused kernel never materialises the logits it
            # transforms).
            raise ValueError(
                "final_softcap does not compose with fused_ce"
            )
        tokens = batch["tokens"]
        out = self(
            params,
            tokens[:, :-1],
            blocks_fn=blocks_fn,
            segment_ids=(
                batch["segment_ids"][:, :-1]
                if batch.get("segment_ids") is not None
                else None
            ),
            positions=(
                batch["positions"][:, :-1]
                if batch.get("positions") is not None
                else None
            ),
            return_aux=True,
            return_hidden=fused_ce,
        )
        mask = batch.get("mask")
        if mask is not None:
            mask = mask[:, 1:]
        if fused_ce:
            h, moe_aux = out
            w = (
                params["embed"].T
                if cfg.tie_embeddings
                else dequantize_tree(params["unembed"], h.dtype)
            )
            loss, aux = fused_softmax_cross_entropy(
                h,
                self.policy.cast_to_compute(w),
                tokens[:, 1:],
                mask=mask,
                z_loss=cfg.z_loss,
            )
        else:
            logits, moe_aux = out
            loss, aux = softmax_cross_entropy(
                logits, tokens[:, 1:], mask=mask, z_loss=cfg.z_loss
            )
        if moe_aux is not None:
            loss = (
                loss
                + cfg.moe_lb_coef * moe_aux["lb"]
                + cfg.moe_rz_coef * moe_aux["rz"]
            )
            aux.update({f"moe_{k}": v for k, v in moe_aux.items()})
        return loss, aux

    # ------------------------------------------------------------- quant
    def quant_spec(self):
        """Params-structured tree of matmul-contraction axes for int8
        weight-only quantization (infer.quant). ``()`` = keep full
        precision: norm scales (tiny, sensitive), the embedding table (it
        feeds a gather, not a matmul), and the MoE router (tiny, and its
        logits pick experts — rounding them moves routing decisions).
        """
        cfg = self.cfg
        blocks = {
            "attn_norm": (),
            "mlp_norm": (),
            # stacked (L, d, h, hd): contraction is the embed axis.
            "wq": (1,),
            "wk": (1,),
            "wv": (1,),
            # (L, h, hd, d): contraction is (heads, head_dim).
            "wo": (1, 2),
        }
        if cfg.qkv_bias:
            blocks["bq"] = blocks["bk"] = blocks["bv"] = ()  # tiny; exact
        if cfg.n_experts:
            blocks["router"] = ()
            blocks["w_gate"] = (2,)  # (L, E, d, m): contract d
            blocks["w_up"] = (2,)
            blocks["w_down"] = (2,)  # (L, E, m, d): contract m
        else:
            blocks["w_gate"] = (1,)  # (L, d, m): contract d
            blocks["w_up"] = (1,)
            blocks["w_down"] = (1,)  # (L, m, d): contract m
        spec = {"embed": (), "blocks": blocks, "final_norm": ()}
        if not cfg.tie_embeddings:
            spec["unembed"] = (0,)  # (d, V): contract d
        return spec

    # ------------------------------------------------------------------ cache
    def init_cache(self, batch_size: int, max_seq_len: int, dtype=jnp.bfloat16):
        """Preallocated stacked KV cache: leaves (layers, b, s_max, kv, hd).

        Contract: callers must keep ``cache_index + q_len <= max_seq_len``.
        Writes past the end are clamped by ``dynamic_update_slice`` (XLA
        semantics — no out-of-bounds error exists inside jit), which would
        silently overwrite the last valid entries — enforce the bound on
        the host side when driving a decode loop.
        """
        if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
            raise ValueError(
                "quantized KV is supported on the PAGED pool only "
                "(init_paged_cache(dtype=jnp.int8)); the dense cache "
                "has no scale channel"
            )
        cfg = self.cfg
        shape = (
            cfg.n_layers, batch_size, max_seq_len, cfg.n_kv_heads,
            cfg.resolved_head_dim,
        )
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def cache_logical_axes(self):
        """Logical axis names of the KV cache leaves — dense
        (layers, batch, seq, kv, hd) and paged (layers, pages, page,
        kv, hd) both map the same way. The serving engines use this to
        shard the cache (kv heads over tp) on a mesh; models without it
        get a replicated cache."""
        return ("layers", None, None, "kv_heads", "head_dim")

    def init_paged_cache(
        self, n_pages: int, page_size: int, dtype=jnp.bfloat16,
        scale_dtype=jnp.float32,
    ):
        """Paged KV pool: leaves (layers, n_pages, page_size, kv, hd).

        Physical pages are shared by all rows via per-row page tables
        (``page_table`` on the forward). Page 0 is the SCRATCH page by
        convention: unallocated table entries point there, stray writes
        land there, and nothing may ever read it (mask those positions).
        Unlike the dense cache, pool capacity is decoupled from
        max_slots × max_len — size it for the expected TOTAL live tokens,
        which is what makes continuous batching memory-efficient.

        ``dtype=jnp.int8`` returns a QUANTIZED pool: int8 K/V plus
        per-(position, kv head) scales ("k_scale"/"v_scale" leaves,
        (layers, pages, page, kv)) — core.qtensor.quantize_kv's format.
        Writes quantize at the scatter, decode dequantizes inside the
        Pallas paged kernel (per-lane score/weight scaling), so the
        pool's HBM footprint AND per-step read are halved vs bf16.
        Scales init to 1.0: an untouched slot dequantizes to exact 0.
        ``scale_dtype=jnp.bfloat16`` halves the scale pool and the
        kernel's per-step scale streams at ~0.2% extra relative error
        (quantize_kv docstring) — the round-5 lever for the measured
        int8-KV latency gap.
        """
        cfg = self.cfg
        shape = (
            cfg.n_layers, n_pages, page_size, cfg.n_kv_heads,
            cfg.resolved_head_dim,
        )
        if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
            if jnp.dtype(dtype) != jnp.int8:
                raise ValueError(
                    f"quantized paged pools are int8 only, got {dtype}"
                )
            if jnp.dtype(scale_dtype) not in (
                jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
            ):
                raise ValueError(
                    f"scale_dtype must be float32 or bfloat16, got "
                    f"{scale_dtype}"
                )
            return {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.ones(shape[:-1], scale_dtype),
                "v_scale": jnp.ones(shape[:-1], scale_dtype),
            }
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _pallas_paged_ok() -> bool:
    """Whether the Pallas paged-decode kernel may be dispatched.

    The kernel is a single-device program: under a multi-device
    activation-sharding mesh the cache pool is sharded (kv heads over
    tp) and a bare ``pallas_call`` would not be partitioned — there the
    decode falls back to the XLA gather path (tp mesh serving keeps
    working, just without the kernel)."""
    from shifu_tpu.parallel.ctx import current_env

    env = current_env()
    return env is None or env.mesh.size == 1


def _decode_attention(q, ck, cv, cache_index, impl, kv_mask=None,
                      window=None, scale=None, softcap=None):
    """Attention over a preallocated cache: valid keys are [0, index + q_len).

    Queries sit at cache slots index .. index + q_len - 1 (slot-space
    causality). ``cache_index`` may be a scalar (whole batch at one
    offset) or a (batch,) vector (continuous batching: per-slot offsets).
    ``kv_mask`` (batch, s_max) additionally hides slots that hold no real
    token (right-padding of ragged prompts). ``window`` may be a TRACED
    scalar (per-layer alternation rides the layer scan); ``scale``
    overrides head_dim**-0.5; ``softcap`` tanh-caps the scores before
    the mask (Gemma-2).
    """
    del impl  # decode is tiny; XLA path is optimal (no S×S materialisation)
    b, q_len, n_heads, head_dim = q.shape
    _, s_max, n_kv, _ = ck.shape
    group = n_heads // n_kv
    qg = q.reshape(b, q_len, n_kv, group, head_dim)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, ck, preferred_element_type=jnp.float32
    ) * (head_dim**-0.5 if scale is None else scale)
    if softcap is not None:
        scores = jnp.tanh(scores / softcap) * softcap
    kj = jnp.arange(s_max)
    if getattr(cache_index, "ndim", 0) == 1:
        qi = cache_index[:, None] + jnp.arange(q_len)[None, :]  # (b, q)
        valid = kj[None, None, :] <= qi[:, :, None]  # (b, q, s)
        if window is not None:
            valid = valid & (kj[None, None, :] > qi[:, :, None] - window)
    else:
        qi = cache_index + jnp.arange(q_len)[:, None]  # (q, 1)
        valid = (kj[None, :] <= qi)[None]  # (1, q, s)
        if window is not None:
            valid = valid & (kj[None, :] > qi - window)[None]
    if kv_mask is not None:
        valid = valid & kv_mask[:, None, :]  # (b, q, s)
    mask = jnp.where(valid, 0.0, NEG_INF)[:, None, None, :, :]
    scores = scores + mask
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    # Cast back to q.dtype: the cache may be wider (e.g. f32 cache under a
    # bf16 compute policy) and promotion would change the residual-stream
    # dtype mid-scan.
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, cv).astype(q.dtype)
    return out.reshape(b, q_len, n_heads, head_dim)
