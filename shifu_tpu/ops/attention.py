"""Attention ops.

``dot_product_attention`` is the XLA reference path: grouped-query causal
attention expressed as two einsums with an f32 softmax between them. XLA
tiles the einsums onto the MXU; for long sequences the pallas flash kernel
(shifu_tpu.ops.pallas.flash_attention) avoids materialising the (S, S)
scores matrix in HBM — select it with ``impl="flash"`` on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0e38  # large finite negative; avoids NaN from (-inf) - (-inf)


def _causal_mask(q_len: int, kv_len: int, dtype=jnp.float32,
                 window: Optional[int] = None):
    """(q_len, kv_len) additive mask; query i attends kv j <= i + offset.

    When q_len < kv_len (decode with a KV cache), queries are aligned to the
    *end* of the KV axis. ``window``: sliding-window attention — query i
    additionally sees only the last ``window`` positions (itself included).
    """
    offset = kv_len - q_len
    qi = jnp.arange(q_len)[:, None]
    kj = jnp.arange(kv_len)[None, :]
    ok = kj <= qi + offset
    if window is not None:
        ok = ok & (kj > qi + offset - window)
    return jnp.where(ok, 0.0, NEG_INF).astype(dtype)


def dot_product_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    impl: str = "xla",
    window: Optional[int] = None,
    softcap: Optional[float] = None,
):
    """Grouped-query attention.

    Args:
      q: (batch, q_len, num_heads, head_dim)
      k/v: (batch, kv_len, num_kv_heads, head_dim); num_heads must be a
        multiple of num_kv_heads (heads are grouped onto kv heads).
      causal: apply a causal mask (queries aligned to the end of kv axis).
      scale: score scale; defaults to head_dim ** -0.5.
      segment_ids: optional (batch, kv_len) int array for packed sequences;
        tokens only attend within their segment. Requires q_len == kv_len.
      impl: "xla" (this file), "flash" (pallas TPU kernel), or "ring"
        (sequence-parallel ring over the sp mesh axis; needs an active
        activation_sharding context with sp > 1 and mesh-divisible
        shapes — see parallel.ring.ring_shardable — else it silently
        falls back to the O(S^2)-memory XLA path). "flash" resolves a
        KERNEL VARIANT per shape class through ops.pallas.registry —
        v0 (the measured defaults) without a tune table, the table's
        winner with one; a softcap class whose winner is the split
        "xla_split" variant re-routes here to the XLA path.
      window: sliding-window attention — query i sees only keys in
        (i - window, i], i.e. the last ``window`` positions INCLUDING
        itself. Requires ``causal=True``. All impls support it: flash
        SKIPS out-of-window KV blocks (O(S·window) compute); ring skips
        fully-out-of-window ring chunks the same way (lax.cond per
        visiting chunk).
      softcap: Gemma-2 tanh attention-logit capping — scores become
        ``softcap * tanh(scores / softcap)`` after the scale and
        BEFORE the mask. Supported by every impl (the flash kernel
        caps each block tile inside its online softmax and carries the
        sech^2 term in the backward; ring caps inside each fold) —
        see docs/attention_kernels.md.

    Returns:
      (batch, q_len, num_heads, head_dim) in q.dtype.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    if impl == "flash":
        if isinstance(window, jax.Array):
            # The flash kernel prunes its grid from a STATIC window; a
            # traced width (the per-layer alternation scalar) cannot
            # reach it. models.Transformer routes alternating stacks
            # through a lax.cond between two STATIC-window kernel
            # calls instead — anything else landing here is a bug.
            raise ValueError(
                "impl='flash' needs a static window; per-layer traced "
                "windows must dispatch via static-window branches "
                "(Transformer._self_attention)"
            )
        from shifu_tpu.ops.pallas import registry as _reg
        from shifu_tpu.ops.pallas.flash_attention import flash_attention

        # Kernel-variant resolution (ops/pallas/registry.py): this
        # dispatch is where a tune table's winner takes effect — v0
        # (= the pre-registry defaults) without one. Resolving HERE
        # rather than inside the kernel lets a winner route a softcap
        # class to the split/XLA path ("xla_split"), the one variant
        # the kernel cannot apply to itself.
        h, hkv = q.shape[2], k.shape[2]
        variant = _reg.resolve(_reg.ShapeClass.flash(
            kv_len=k.shape[1], head_dim=q.shape[3],
            gqa=h // max(1, hkv), window=window, softcap=softcap,
            dtype=q.dtype,
        ))
        if variant.p.get("impl") != "xla":
            return flash_attention(
                q, k, v, causal=causal, scale=scale,
                segment_ids=segment_ids, window=window,
                softcap=softcap, variant=variant,
            )
        impl = "xla"  # split-softcap winner: fall through
    if impl == "ring":
        # Sequence-parallel ring attention over the sp mesh axis. Needs an
        # active activation_sharding context to discover the mesh; falls
        # back to the XLA path when there is no sp sharding to ride or the
        # shapes don't divide the mesh (ring_shardable).
        from shifu_tpu.parallel.ctx import current_env
        from shifu_tpu.parallel.ring import (
            ring_attention_sharded,
            ring_shardable,
        )

        env = current_env()
        if env is not None and ring_shardable(env.mesh, q.shape, k.shape):
            return ring_attention_sharded(
                q, k, v, env.mesh, causal=causal, scale=scale,
                segment_ids=segment_ids, window=window, softcap=softcap,
            )
        impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown attention impl: {impl!r}")

    b, q_len, n_heads, head_dim = q.shape
    _, kv_len, n_kv, _ = k.shape
    if n_heads % n_kv:
        raise ValueError(f"num_heads={n_heads} not divisible by kv={n_kv}")
    group = n_heads // n_kv
    if scale is None:
        scale = head_dim**-0.5

    qg = q.reshape(b, q_len, n_kv, group, head_dim)
    # Scores in f32: bf16 logits lose too much around the softmax max-shift.
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if softcap is not None:
        # Gemma-2 tanh soft-capping: bounds the logits to (-cap, cap)
        # BEFORE the additive mask (the -inf mask must stay -inf).
        scores = jnp.tanh(scores / softcap) * softcap

    if causal:
        scores = scores + _causal_mask(q_len, kv_len, window=window)
    if segment_ids is not None:
        if q_len != kv_len:
            raise ValueError("segment_ids requires q_len == kv_len")
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = scores + jnp.where(same, 0.0, NEG_INF)[:, None, None, :, :]

    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(b, q_len, n_heads, head_dim)
