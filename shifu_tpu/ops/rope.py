"""Rotary position embeddings.

Split-half convention (first half of head_dim pairs with second half), f32
rotation math. Frequencies are computed once per forward at trace time —
they are constants under jit, so XLA hoists them.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    positions,
    *,
    theta: float = 10000.0,
    scaling=None,
):
    """Return (sin, cos) of shape positions.shape + (head_dim // 2,).

    ``scaling``: optional Llama-3.1-style frequency scaling, a 4-tuple
    ``(factor, low_freq_factor, high_freq_factor, original_context_len)``
    — long-wavelength components are slowed by ``factor``, short ones
    kept, and the band between smoothly interpolated (matches the HF
    ``rope_type="llama3"`` implementation exactly).
    """
    if head_dim % 2:
        raise ValueError(f"head_dim must be even, got {head_dim}")
    exponent = jnp.arange(head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    inv_freq = theta**-exponent  # (head_dim/2,)
    if scaling is not None:
        factor, low_fac, high_fac, orig_len = scaling
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wl = orig_len / low_fac  # longest unscaled wavelength
        high_wl = orig_len / high_fac
        smooth = (orig_len / wavelen - low_fac) / (high_fac - low_fac)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        mixed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > low_wl,  # long wavelength: fully scaled
            inv_freq / factor,
            jnp.where(wavelen < high_wl, inv_freq, mixed),
        )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x, sin, cos):
    """Rotate ``x`` of shape (..., seq, heads, head_dim).

    ``sin``/``cos`` have shape (..., seq, head_dim // 2); a heads axis is
    inserted for broadcast.
    """
    sin = sin[..., :, None, :]
    cos = cos[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
