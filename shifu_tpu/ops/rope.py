"""Rotary position embeddings.

Split-half convention (first half of head_dim pairs with second half), f32
rotation math. Frequencies are computed once per forward at trace time —
they are constants under jit, so XLA hoists them (the "dynamic" NTK
variant alone depends on the *values* of positions and stays a traced
computation).

Context-extension frequency scaling (``scaling``) follows the
HuggingFace ``rope_type`` semantics exactly (verified against
``transformers.modeling_rope_utils`` in tests/test_ops.py) so converted
checkpoints keep their logits. Supported, as hashable tagged tuples
(dataclass-config friendly — dicts are not hashable):

  ``("linear", factor)``
      Position-interpolation: every frequency divided by ``factor``.
  ``("dynamic", factor, original_context_len)``
      Dynamic NTK: the wavelength base is stretched as the sequence
      grows past the original context, ``base' = base * ((factor *
      L / orig - (factor - 1)) ** (d / (d - 2)))`` with L the largest
      position in this call (>= orig).
  ``("yarn", factor, beta_fast, beta_slow, original_context_len,
     attention_factor[, truncate])``
      YaRN (arXiv 2309.00071): interpolate low-frequency dims by
      ``factor``, keep high-frequency dims, linear-ramp between the
      correction dims found from beta_fast/beta_slow rotations; cos/sin
      additionally scaled by ``attention_factor`` (None = the paper's
      ``0.1 * ln(factor) + 1``). ``truncate`` (default True) floors/
      ceils the correction dims as HF does; False keeps them fractional.
  ``("llama3", factor, low_freq_factor, high_freq_factor,
     original_context_len)``
      Llama-3.1 wavelength-banded scaling. A legacy bare 4-tuple of
      numbers means the same thing.
  ``("longrope", short_factors, long_factors, original_context_len,
     factor, attention_factor)``
      LongRoPE (Phi-3): per-dimension frequency divisors — the
      ``short_factors`` tuple (length head_dim/2) applies while every
      position fits the original context, ``long_factors`` once the
      call's max position exceeds it (a traced switch); cos/sin scaled
      by ``attention_factor`` (None = ``sqrt(1 + ln(factor)/ln(orig))``).
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def _llama3_inv_freq(inv_freq, factor, low_fac, high_fac, orig_len):
    wavelen = 2.0 * jnp.pi / inv_freq
    low_wl = orig_len / low_fac  # longest unscaled wavelength
    high_wl = orig_len / high_fac
    smooth = (orig_len / wavelen - low_fac) / (high_fac - low_fac)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    mixed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return jnp.where(
        wavelen > low_wl,  # long wavelength: fully scaled
        inv_freq / factor,
        jnp.where(wavelen < high_wl, inv_freq, mixed),
    )


def get_mscale(scale: float, m: float = 1.0) -> float:
    """YaRN attention-temperature scale: 0.1·m·ln(scale) + 1 (1 if
    scale <= 1). Single home for the formula — convert.py's DeepSeek
    mscale/mscale_all_dim path uses it too."""
    return 0.1 * m * math.log(scale) + 1.0 if scale > 1 else 1.0


def _yarn_inv_freq(
    head_dim, theta, factor, beta_fast, beta_slow, orig_len, truncate=True
):
    def correction_dim(n_rot):
        # Dim whose wavelength completes n_rot rotations over orig_len.
        return (
            head_dim
            * math.log(orig_len / (n_rot * 2 * math.pi))
            / (2 * math.log(theta))
        )

    low, high = correction_dim(beta_fast), correction_dim(beta_slow)
    if truncate:
        low, high = math.floor(low), math.ceil(high)
    low = max(low, 0)
    high = min(high, head_dim - 1)
    if low == high:
        high += 0.001  # avoid the ramp singularity (HF convention)
    ramp = jnp.clip(
        (jnp.arange(head_dim // 2, dtype=jnp.float32) - low) / (high - low),
        0.0,
        1.0,
    )
    extrap_frac = 1.0 - ramp  # 1 at high-frequency dims: keep as-is
    exponent = (
        jnp.arange(head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    )
    pos_freq = theta**exponent
    return (1.0 / (factor * pos_freq)) * (1.0 - extrap_frac) + (
        1.0 / pos_freq
    ) * extrap_frac


def rope_frequencies(
    head_dim: int,
    positions,
    *,
    theta: float = 10000.0,
    scaling=None,
    regime_len=None,
):
    """Return (sin, cos) of shape positions.shape + (head_dim // 2,).

    ``scaling``: optional context-extension frequency scaling — a tagged
    tuple, see the module docstring for the supported variants.

    ``regime_len``: optional override of the sequence length the
    length-SENSITIVE scalings ("dynamic", "longrope") key their regime
    off (default: ``max(positions, axis=-1) + 1``). A chunked prefill
    knows the prompt's FINAL length at admission while each chunk's
    positions top out mid-prompt — passing the final length here makes
    every chunk bake the same frequencies the one-shot prefill would.
    Scalar or broadcastable to positions' leading axes.
    """
    if head_dim % 2:
        raise ValueError(f"head_dim must be even, got {head_dim}")
    exponent = jnp.arange(head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    inv_freq = theta**-exponent  # (head_dim/2,)
    mscale = 1.0
    if scaling is not None:
        kind, args = scaling[0], scaling[1:]
        if not isinstance(kind, str):  # legacy bare 4-tuple = llama3
            kind, args = "llama3", tuple(scaling)
        if kind == "llama3":
            inv_freq = _llama3_inv_freq(inv_freq, *args)
        elif kind == "linear":
            (factor,) = args
            inv_freq = inv_freq / factor
        elif kind == "dynamic":
            factor, orig_len = args
            # Traced, value-dependent: the base stretches with the
            # longest position used — PER ROW when positions are (b, s),
            # so one long request in a served batch cannot stretch the
            # short requests sharing its decode dispatch. (HF applies
            # one global stretch per forward; per-row is strictly more
            # faithful to the single-request semantics its parity tests
            # pin, and identical for 1-D positions.)
            used_len = (
                jnp.max(positions, axis=-1, keepdims=True).astype(
                    jnp.float32
                )
                + 1.0
                if regime_len is None
                else jnp.broadcast_to(
                    jnp.asarray(regime_len, jnp.float32),
                    positions.shape[:-1],
                )[..., None]
            )
            seq_len = jnp.maximum(used_len, float(orig_len))[
                ..., None
            ]  # (..., 1, 1): broadcasts against (d/2,)
            base = theta * (factor * seq_len / orig_len - (factor - 1.0)) ** (
                head_dim / (head_dim - 2)
            )
            inv_freq = base**-exponent
        elif kind == "yarn":
            factor, beta_fast, beta_slow, orig_len, attn_factor = args[:5]
            truncate = args[5] if len(args) > 5 else True
            inv_freq = _yarn_inv_freq(
                head_dim, theta, factor, beta_fast, beta_slow, orig_len,
                truncate,
            )
            mscale = (
                attn_factor if attn_factor is not None else get_mscale(factor)
            )
        elif kind == "longrope":
            short, long_, orig_len, factor, attn_factor = args
            if len(short) != head_dim // 2 or len(long_) != head_dim // 2:
                raise ValueError(
                    f"longrope factor vectors must have length "
                    f"head_dim/2={head_dim // 2}, got "
                    f"{len(short)}/{len(long_)}"
                )
            # NOTE: callers that right-pad (prefill buckets) must clamp
            # positions to the real length, or padding flips the regime.
            # The switch is PER ROW for (b, s) positions (same rationale
            # as "dynamic" above: co-batched requests must not flip each
            # other); a request whose own decode crosses orig_len still
            # flips mid-request, inherent to longrope-with-cache.
            used = (
                jnp.max(positions, axis=-1, keepdims=True) + 1
                if regime_len is None
                else jnp.broadcast_to(
                    jnp.asarray(regime_len, jnp.int32),
                    positions.shape[:-1],
                )[..., None]
            )
            over = (used > orig_len)[..., None]  # (..., 1, 1)
            ext = jnp.where(
                over,
                jnp.asarray(long_, jnp.float32),
                jnp.asarray(short, jnp.float32),
            )
            inv_freq = inv_freq / ext
            mscale = (
                attn_factor
                if attn_factor is not None
                else (
                    math.sqrt(1.0 + math.log(factor) / math.log(orig_len))
                    if factor > 1.0
                    else 1.0
                )
            )
        else:
            raise ValueError(f"unknown rope scaling kind {kind!r}")
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.sin(angles) * mscale, jnp.cos(angles) * mscale


def apply_rope(x, sin, cos):
    """Rotate ``x`` of shape (..., seq, heads, head_dim).

    ``sin``/``cos`` have shape (..., seq, head_dim // 2); a heads axis is
    inserted for broadcast. YaRN's attention_factor is pre-folded into
    the sin/cos tables (rope_frequencies), exactly as HF does — rotating
    both q and k with the scaled tables yields the attention-temperature
    scaling of the YaRN paper.
    """
    sin = sin[..., :, None, :]
    cos = cos[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
