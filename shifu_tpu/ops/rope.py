"""Rotary position embeddings.

Split-half convention (first half of head_dim pairs with second half), f32
rotation math. Frequencies are computed once per forward at trace time —
they are constants under jit, so XLA hoists them.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, positions, *, theta: float = 10000.0):
    """Return (sin, cos) of shape positions.shape + (head_dim // 2,)."""
    if head_dim % 2:
        raise ValueError(f"head_dim must be even, got {head_dim}")
    exponent = jnp.arange(head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    inv_freq = theta**-exponent  # (head_dim/2,)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x, sin, cos):
    """Rotate ``x`` of shape (..., seq, heads, head_dim).

    ``sin``/``cos`` have shape (..., seq, head_dim // 2); a heads axis is
    inserted for broadcast.
    """
    sin = sin[..., :, None, :]
    cos = cos[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
