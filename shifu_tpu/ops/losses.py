"""Loss ops.

Cross-entropy takes logits in any float dtype, reduces in f32, and supports
a z-loss term (pulls log-Z toward 0, stabilising bf16 logits over long runs)
and a validity mask for padded / packed batches.

``fused_softmax_cross_entropy`` additionally fuses the unembed matmul into
the loss, chunked over the sequence: the (b, s, vocab) logits tensor —
the single largest array in a training step (2 GB+ at b8 s2048 v32k f32)
— is never materialised in HBM; each chunk's logits live only inside a
rematerialised scan step and are recomputed for the backward. Same math,
same f32 reductions, minus gigabytes of HBM traffic and residency.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from shifu_tpu.parallel.ctx import constrain


def softmax_cross_entropy(
    logits,
    labels,
    *,
    mask: Optional[jax.Array] = None,
    z_loss: float = 0.0,
):
    """Mean token cross-entropy.

    Args:
      logits: (..., vocab), any float dtype.
      labels: (...) int token ids.
      mask: optional (...) weights; 0 drops a position. Mean is over the
        mask sum, not the full shape.
      z_loss: coefficient for log(Z)^2 regulariser (0 disables).

    Returns:
      (loss, aux) where aux = {"ce": ..., "z": ..., "denominator": ...}.
    """
    logits = logits.astype(jnp.float32)
    log_z = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(
        logits, labels[..., None], axis=-1
    ).squeeze(-1)
    ce = log_z - label_logits
    z = jnp.square(log_z)

    if mask is None:
        denom = jnp.asarray(ce.size, jnp.float32)
        ce_sum = jnp.sum(ce)
        z_sum = jnp.sum(z)
    else:
        w = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        ce_sum = jnp.sum(ce * w)
        z_sum = jnp.sum(z * w)

    ce_mean = ce_sum / denom
    z_mean = z_sum / denom
    loss = ce_mean + z_loss * z_mean
    return loss, {"ce": ce_mean, "z": z_mean, "denominator": denom}


def fused_softmax_cross_entropy(
    h,
    unembed,
    labels,
    *,
    mask: Optional[jax.Array] = None,
    z_loss: float = 0.0,
    chunk: int = 512,
):
    """Mean token cross-entropy with the unembed matmul fused in.

    Args:
      h: (b, s, d) final hidden states (post final-norm), any float dtype.
      unembed: (d, vocab) projection (pass ``embed.T`` for tied
        embeddings; under jit the transpose is a layout change XLA folds
        into the matmul).
      labels: (b, s) int token ids.
      mask / z_loss: as :func:`softmax_cross_entropy`.
      chunk: sequence positions per scan step. Each step materialises
        only a (b, chunk, vocab) logits block; the step is
        rematerialised so the backward recomputes it instead of saving
        it. 512 is throughput-neutral vs unfused on v5e while bounding
        transient logits to ~b*chunk*vocab*4 bytes (smaller chunks
        trade a few % of throughput for tighter memory).

    Returns: (loss, aux) — identical contract (and, up to summation
    order, identical values) to computing full logits then
    :func:`softmax_cross_entropy`.
    """
    b, s, d = h.shape
    chunk = min(chunk, s)
    pad = (-s) % chunk
    w = (
        mask.astype(jnp.float32)
        if mask is not None
        else jnp.ones((b, s), jnp.float32)
    )
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        w = jnp.pad(w, ((0, 0), (0, pad)))  # pad positions weigh 0
    n = (s + pad) // chunk
    hc = h.reshape(b, n, chunk, d).swapaxes(0, 1)  # (n, b, chunk, d)
    lc = labels.reshape(b, n, chunk).swapaxes(0, 1)
    wc = w.reshape(b, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def body(carry, xs):
        ce_sum, z_sum = carry
        h_c, lbl_c, w_c = xs
        logits = jnp.einsum(
            "bcd,dv->bcv", h_c, unembed, preferred_element_type=jnp.float32
        )
        logits = constrain(logits, ("batch", "seq", "act_vocab"))
        log_z = jax.nn.logsumexp(logits, axis=-1)
        label_logits = jnp.take_along_axis(
            logits, lbl_c[..., None], axis=-1
        ).squeeze(-1)
        ce_sum = ce_sum + jnp.sum((log_z - label_logits) * w_c)
        z_sum = z_sum + jnp.sum(jnp.square(log_z) * w_c)
        return (ce_sum, z_sum), None

    (ce_sum, z_sum), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (hc, lc, wc)
    )
    denom = (
        jnp.asarray(b * s, jnp.float32)
        if mask is None
        else jnp.maximum(jnp.sum(w), 1.0)
    )
    ce_mean = ce_sum / denom
    z_mean = z_sum / denom
    loss = ce_mean + z_loss * z_mean
    return loss, {"ce": ce_mean, "z": z_mean, "denominator": denom}
