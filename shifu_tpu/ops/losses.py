"""Loss ops.

Cross-entropy takes logits in any float dtype, reduces in f32, and supports
a z-loss term (pulls log-Z toward 0, stabilising bf16 logits over long runs)
and a validity mask for padded / packed batches.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def softmax_cross_entropy(
    logits,
    labels,
    *,
    mask: Optional[jax.Array] = None,
    z_loss: float = 0.0,
):
    """Mean token cross-entropy.

    Args:
      logits: (..., vocab), any float dtype.
      labels: (...) int token ids.
      mask: optional (...) weights; 0 drops a position. Mean is over the
        mask sum, not the full shape.
      z_loss: coefficient for log(Z)^2 regulariser (0 disables).

    Returns:
      (loss, aux) where aux = {"ce": ..., "z": ..., "denominator": ...}.
    """
    logits = logits.astype(jnp.float32)
    log_z = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(
        logits, labels[..., None], axis=-1
    ).squeeze(-1)
    ce = log_z - label_logits
    z = jnp.square(log_z)

    if mask is None:
        denom = jnp.asarray(ce.size, jnp.float32)
        ce_sum = jnp.sum(ce)
        z_sum = jnp.sum(z)
    else:
        w = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        ce_sum = jnp.sum(ce * w)
        z_sum = jnp.sum(z * w)

    ce_mean = ce_sum / denom
    z_mean = z_sum / denom
    loss = ce_mean + z_loss * z_mean
    return loss, {"ce": ce_mean, "z": z_mean, "denominator": denom}
