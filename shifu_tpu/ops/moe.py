"""Mixture-of-Experts routing: top-k capacity-based dispatch.

Two formulations over ONE set of routing decisions:

  * :func:`route_top_k` — the classic dense dispatch/combine-einsum
    formulation (GShard / Switch on TPU): routing produces two dense
    (b, s, E, C) tensors — ``dispatch`` (0/1 token→slot assignment) and
    ``combine`` (dispatch × gate weight) — and the model contracts them
    against the token stream. Simple and exactly auditable, but the two
    contractions burn O(b·s·E·C·d) MACs of pure data movement ON TOP of
    the expert FFN flops; at top-2-of-8 that overhead is comparable to
    the expert compute itself (the measured moe_mfu gap). Kept as the
    CORRECTNESS ORACLE behind ``TransformerConfig(moe_impl="einsum")``.
  * :func:`route_top_k_grouped` — the sorted/grouped formulation (the
    default fast path): the SAME routing decisions are returned in
    index/weight form ((expert, slot) per assignment), the model builds
    the (E, b, C, d) expert buffers through ONE inverse-permutation
    gather (equivalent to a stable sort of assignments by (expert,
    slot), computed without an argsort), runs the identical grouped
    expert matmuls, and scatters results back through the forward
    permutation. Dispatch/combine cost drops from two O(b·s·E·C·d)
    einsums to two O((E·C + s·k)·d)-element gathers — no MXU flops at
    all. Everything stays fixed-shape, so it jits once and shards
    exactly like the einsum path.

Shared properties:

  * Under a mesh, the E axis of the expert buffers is sharded over the
    ``ep`` mesh axis by an activation constraint; XLA inserts the
    all-to-all between the (batch-sharded) token layout and the
    (expert-sharded) buffer layout on its own (both formulations pin
    the same (E, b, C, d) buffer layout, so the collective pattern is
    identical).
  * Capacity C = ceil(capacity_factor * s * k / E) bounds per-expert work;
    overflow tokens are dropped (their combine weight is 0, so the residual
    stream passes them through untouched). Priority is choice-major: every
    token's 1st choice beats any token's 2nd choice (GShard order) — the
    grouped path reuses the einsum path's cumsum slot assignment verbatim,
    so the two paths drop EXACTLY the same assignments.

Which formulation the model actually runs is a KERNEL VARIANT (round
10): ``Transformer._moe_ffn`` resolves the "moe" shape class (seq
bucket, dim, experts, top_k, dtype) through ops.pallas.registry — v0
is the grouped path, and a tune table (``shifu_tpu tune --legs moe``)
may route a class where the dense form measured faster (tiny E·C) to
the einsum variant. The two are bit-identical routings (shared
``_routing_decisions``), so the swap is numerics-free by construction;
explicit ``moe_impl="einsum"`` remains the unconditional oracle switch.

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md) — there is no reference MoE implementation to match.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_capacity(seq_len: int, top_k: int, n_experts: int, factor: float) -> int:
    """Static per-expert buffer length for one batch row."""
    return max(1, int(-(-seq_len * top_k * factor // n_experts)))


def _routing_decisions(router_logits, top_k: int, capacity: int,
                       normalize_weights: bool):
    """Shared routing core for both dispatch formulations.

    Returns ``(gate_vals, gate_idx, expert_mask, mask_ks, pos, aux)``:
    gate_vals/gate_idx (b, s, k) f32/int32; expert_mask (b, s, k, E)
    one-hot; mask_ks its choice-major (b, k·s, E) flattening (k
    outermost, so every token's 1st choice occupies slots before any
    2nd choice — GShard priority); ``pos`` (b, k·s, E) the cumsum slot
    index each assignment takes within its expert; ``aux`` the loss
    dict. Keeping this in ONE place is what makes the grouped path a
    provably identical routing to the einsum oracle.
    """
    b, s, n_experts = router_logits.shape
    logits = router_logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # (b, s, k)
    if normalize_weights:
        gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # (b, s, k, E) one-hot of each token's k choices.
    expert_mask = jax.nn.one_hot(gate_idx, n_experts, dtype=jnp.float32)

    # Choice-major priority: flatten (k, s) with k outermost so all 1st
    # choices occupy slots before any 2nd choice.
    mask_ks = expert_mask.transpose(0, 2, 1, 3).reshape(b, top_k * s, n_experts)
    pos = jnp.cumsum(mask_ks, axis=1) - mask_ks  # slot index within expert

    # Load balance (Switch eq. 4, computed over all k assignments): with
    # f_e the fraction of assignments routed to e and p_e the mean router
    # prob, E·Σ f_e p_e is 1.0 at perfectly uniform routing.
    f = jnp.mean(expert_mask, axis=(0, 1, 2))  # fraction per expert, Σ=1
    p = jnp.mean(probs, axis=(0, 1))
    lb = n_experts * jnp.sum(f * p)
    rz = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    keep = (pos < capacity).astype(jnp.float32) * mask_ks
    routed = jnp.sum(keep) / jnp.maximum(jnp.sum(mask_ks), 1.0)
    aux = {"lb": lb, "rz": rz, "dropped": 1.0 - routed}
    return gate_vals, gate_idx, expert_mask, mask_ks, pos, aux


def route_top_k(
    router_logits: jax.Array,
    top_k: int,
    capacity: int,
    *,
    normalize_weights: bool = True,
):
    """Top-k routing with per-row expert capacity (dense-einsum form).

    Args:
      router_logits: (b, s, E), any float dtype (softmax runs in f32).
      top_k: experts per token.
      capacity: per-expert slots per batch row (see :func:`moe_capacity`).
      normalize_weights: renormalise the k gate weights to sum to 1
        (Mixtral convention); otherwise raw softmax probabilities (Switch).

    Returns:
      (dispatch, combine, aux):
        dispatch: (b, s, E, C) f32 in {0, 1} — token→(expert, slot).
        combine:  (b, s, E, C) f32 — dispatch × gate weight.
        aux: {"lb": load-balance loss (→1.0 at uniform routing),
              "rz": router z-loss (mean logsumexp²),
              "dropped": fraction of assignments dropped for capacity}.
    """
    b, s, n_experts = router_logits.shape
    gate_vals, _, _, mask_ks, pos, aux = _routing_decisions(
        router_logits, top_k, capacity, normalize_weights
    )
    keep = (pos < capacity).astype(jnp.float32) * mask_ks

    slot_hot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch_ks = keep[..., None] * slot_hot  # (b, k*s, E, C)
    dispatch = (
        dispatch_ks.reshape(b, top_k, s, n_experts, capacity)
        .transpose(0, 2, 1, 3, 4)
    )  # (b, s, k, E, C)
    combine = jnp.sum(dispatch * gate_vals[..., None, None], axis=2)
    dispatch = jnp.sum(dispatch, axis=2)
    return dispatch, combine, aux


def route_top_k_grouped(
    router_logits: jax.Array,
    top_k: int,
    capacity: int,
    *,
    normalize_weights: bool = True,
):
    """Top-k routing in SORTED/GROUPED index form (the fast path).

    Identical routing decisions to :func:`route_top_k` (shared core:
    same softmax/top-k, same choice-major cumsum slot assignment, same
    aux losses) — but instead of materialising (b, s, E, C) one-hot
    tensors, each of the b·s·k assignments is described by the
    (expert, slot) cell it occupies. The model then builds expert
    buffers with a gather through the inverse permutation and combines
    through the forward permutation (``Transformer._moe_ffn_grouped``),
    touching O((E·C + s·k)·d) elements instead of O(b·s·E·C·d) MACs.

    Returns:
      (expert_idx, slot_idx, weights, keep, aux):
        expert_idx: (b, s, k) int32 — each assignment's expert.
        slot_idx:   (b, s, k) int32 — its slot within that expert's
          per-row capacity-C buffer (valid only where ``keep``).
        weights:    (b, s, k) f32 — gate weights (NOT zeroed for
          dropped assignments; mask with ``keep`` at the combine).
        keep:       (b, s, k) bool — assignment fit under capacity.
        aux: same dict as :func:`route_top_k`.
    """
    b, s, _ = router_logits.shape
    gate_vals, gate_idx, _, mask_ks, pos, aux = _routing_decisions(
        router_logits, top_k, capacity, normalize_weights
    )
    # Reduce the (b, k*s, E) slot grid to per-assignment scalars (each
    # assignment has exactly one expert, so the sum picks its column),
    # then undo the choice-major flattening back to (b, s, k).
    pos_a = jnp.sum(pos * mask_ks, axis=-1)  # (b, k*s)
    slot = (
        pos_a.reshape(b, top_k, s).transpose(0, 2, 1).astype(jnp.int32)
    )
    keep = (pos_a < capacity).reshape(b, top_k, s).transpose(0, 2, 1)
    return gate_idx.astype(jnp.int32), slot, gate_vals, keep, aux
