"""Mixture-of-Experts routing: top-k capacity-based dispatch.

TPU-first design — the classic dispatch/combine-einsum formulation (as in
GShard / Switch on TPU) rather than gather/scatter:

  * Routing produces two dense (b, s, E, C) tensors — ``dispatch`` (0/1
    token→slot assignment) and ``combine`` (dispatch × gate weight). Expert
    input buffers are then a single einsum, expert FFNs run batched over a
    leading E axis (one big MXU matmul per projection), and outputs come
    back with a second einsum. Everything is static-shaped, so it jits once.
  * Under a mesh, the E axis of the expert buffers is sharded over the
    ``ep`` mesh axis by an activation constraint; XLA inserts the
    all-to-all between the (batch-sharded) token layout and the
    (expert-sharded) buffer layout on its own.
  * Capacity C = ceil(capacity_factor * s * k / E) bounds per-expert work;
    overflow tokens are dropped (their combine weight is 0, so the residual
    stream passes them through untouched). Priority is choice-major: every
    token's 1st choice beats any token's 2nd choice (GShard order).

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md) — there is no reference MoE implementation to match.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_capacity(seq_len: int, top_k: int, n_experts: int, factor: float) -> int:
    """Static per-expert buffer length for one batch row."""
    return max(1, int(-(-seq_len * top_k * factor // n_experts)))


def route_top_k(
    router_logits: jax.Array,
    top_k: int,
    capacity: int,
    *,
    normalize_weights: bool = True,
):
    """Top-k routing with per-row expert capacity.

    Args:
      router_logits: (b, s, E), any float dtype (softmax runs in f32).
      top_k: experts per token.
      capacity: per-expert slots per batch row (see :func:`moe_capacity`).
      normalize_weights: renormalise the k gate weights to sum to 1
        (Mixtral convention); otherwise raw softmax probabilities (Switch).

    Returns:
      (dispatch, combine, aux):
        dispatch: (b, s, E, C) f32 in {0, 1} — token→(expert, slot).
        combine:  (b, s, E, C) f32 — dispatch × gate weight.
        aux: {"lb": load-balance loss (→1.0 at uniform routing),
              "rz": router z-loss (mean logsumexp²),
              "dropped": fraction of assignments dropped for capacity}.
    """
    b, s, n_experts = router_logits.shape
    logits = router_logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # (b, s, k)
    if normalize_weights:
        gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # (b, s, k, E) one-hot of each token's k choices.
    expert_mask = jax.nn.one_hot(gate_idx, n_experts, dtype=jnp.float32)

    # Choice-major priority: flatten (k, s) with k outermost so all 1st
    # choices occupy slots before any 2nd choice.
    mask_ks = expert_mask.transpose(0, 2, 1, 3).reshape(b, top_k * s, n_experts)
    pos = jnp.cumsum(mask_ks, axis=1) - mask_ks  # slot index within expert
    keep = (pos < capacity).astype(jnp.float32) * mask_ks

    slot_hot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch_ks = keep[..., None] * slot_hot  # (b, k*s, E, C)
    dispatch = (
        dispatch_ks.reshape(b, top_k, s, n_experts, capacity)
        .transpose(0, 2, 1, 3, 4)
    )  # (b, s, k, E, C)
    combine = jnp.sum(dispatch * gate_vals[..., None, None], axis=2)
    dispatch = jnp.sum(dispatch, axis=2)

    # Load balance (Switch eq. 4, computed over all k assignments): with
    # f_e the fraction of assignments routed to e and p_e the mean router
    # prob, E·Σ f_e p_e is 1.0 at perfectly uniform routing.
    f = jnp.mean(expert_mask, axis=(0, 1, 2))  # fraction per expert, Σ=1
    p = jnp.mean(probs, axis=(0, 1))
    lb = n_experts * jnp.sum(f * p)
    rz = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    routed = jnp.sum(keep) / jnp.maximum(jnp.sum(mask_ks), 1.0)
    aux = {"lb": lb, "rz": rz, "dropped": 1.0 - routed}
    return dispatch, combine, aux
