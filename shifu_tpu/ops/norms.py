"""Normalisation ops.

RMSNorm is computed in float32 regardless of input dtype (bf16 mean-of-squares
underflows badly at large widths) and cast back, which XLA fuses into a single
VPU kernel around the adjacent matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, scale, *, eps: float = 1e-6):
    """y = x / rms(x) * (1 + scale). ``scale`` is zero-initialised.

    The (1 + scale) parameterisation keeps the parameter's init at zero,
    which plays better with weight decay masks than ones-init.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    y = y * (1.0 + scale.astype(jnp.float32))
    return y.astype(orig_dtype)
