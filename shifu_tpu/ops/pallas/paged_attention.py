"""Pallas TPU paged-attention decode kernel.

Serving decode on the paged engine is HBM-bandwidth-bound: each step must
read every live KV page once. The XLA fallback (models/transformer.py
``_paged_block_attention``) materialises the gather ``pool[page_table]``
as a (b, pages_per_row * page_size, kv, hd) intermediate in HBM and then
reads it again inside attention — ~3x the compulsory traffic (write the
gathered copy, read it back, plus the pool read itself). This kernel
reads each page exactly once, straight from the pool:

  * the page table and per-row lengths are **scalar-prefetched**
    (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index maps
    resolve logical page ``j`` of row ``b`` to its physical page
    ``table[b, j]`` at DMA-issue time — the gather never exists as a
    tensor;
  * grid is (batch, ceil(pages_per_row / U)) with U pages fetched per
    step (U BlockSpec'd inputs each); every page is shared by ALL query
    heads of the row, so GQA reads each page once, not once per head;
  * index maps clamp the logical page to the row's last live page, so
    grid steps past a short row's length re-issue the same block index —
    Mosaic elides the repeat DMA, making per-row traffic O(row length),
    not O(pages_per_row);
  * scores for every head against one page are ONE dot: the page block
    (ps, kv, hd) reinterprets as (ps*kv, hd) — kv*hd is already the
    native (8, 128)-tiled layout, so the reshape is free — and
    q (heads, hd) contracts against it in a single MXU op. Lanes whose
    kv head doesn't serve the query head are masked to NEG_INF; their
    exp underflows to exactly 0, so they add nothing to the normaliser
    or the accumulator. Decode is DMA-bound — the kv-fold FLOP waste is
    invisible, and it removes per-head strided slices and per-head
    scratch read-modify-writes entirely;
  * online softmax (running max / normaliser / f32 accumulator) is
    carried in registers across the U unrolled pages and hits VMEM
    scratch once per grid step; the output block is written once, at
    the last step. Fully-masked (dead) steps are exact no-ops (alpha=1,
    p=0), so there is no in-kernel control flow at all.

Masking reproduces the engine's slot-space semantics exactly: key
position ``pos`` is visible iff ``pos <= lengths[b]`` (the current
token was scattered at ``lengths[b]`` before the call), optionally
``pos > lengths[b] - window`` (sliding window) and ``kv_mask[b, pos]``.

Layout contract matches the caller (models/transformer.py paged decode):
q (b, n_heads, hd) — one decode token per row, already RoPE'd; pool
(n_pages, page_size, kv, hd) — POST-scatter (current token written);
page_table (b, pages_per_row) int32; lengths (b,) int32. Page 0 is the
engine's scratch page; rows whose table entries point there are hidden
by the length mask, never read.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from shifu_tpu.ops.attention import NEG_INF

# Lane-replicated scratch width for the per-head running max/normaliser
# (see ops/pallas/flash_attention.py — same convention).
_LANES = 128

# Floor for the running max. Strictly above NEG_INF (-2e38) and strictly
# below any real score, so exp(NEG_INF - floor) underflows to exactly 0:
# a fully-masked page (or a row kv_mask hid entirely) contributes
# nothing to the normaliser or the accumulator in EVERY scratch state.
# Initialising the running max at NEG_INF itself would make the first
# fully-masked page compute p = exp(NEG_INF - NEG_INF) = 1 on every
# lane and average stale V pages into the output.
_MASK_FLOOR = -1e30


def _decode_kernel(
    scale, window, n_kv, group, unroll, ps, has_mask, has_scale, heads,
    int8_qk,
    *refs,
):
    """One (row, page-group) grid step: U pages against all query rows.

    refs: table_ref, len_ref, layer_ref (scalar prefetch), q_ref
    (1, qw*heads, hd), U k_refs + U v_refs (1, 1, ps*n_kv, hd) each,
    [ks_ref + vs_ref (1, 1, U*ps*n_kv) f32 — int8-pool per-lane scales,
    pre-gathered into the row's LOGICAL layout like the mask: one DMA
    per grid step, not one per page — per-page scale blocks measured
    SLOWER than bf16 KV (decode compute per grid step is tiny, so DMA
    issue count dominates)], [mask_ref (1, 1, U*ps*n_kv) — pre-expanded
    kv-interleaved], o_ref (1, qw*heads, hd), scratch m/l
    (qw*heads, _LANES) and acc (qw*heads, hd).

    MULTI-QUERY (qw > 1, the speculative-verify / batch-chunk shape):
    the qw chunk queries FOLD into the row axis — row r is query offset
    ``t = r // heads``, head ``r % heads``, sitting at slot position
    ``lengths[b] + t``. Per-row causality rides the same lane mask that
    already handles GQA head matching, the pages still stream exactly
    once for ALL queries and heads, and qw == 1 reduces to the plain
    decode kernel (one extra iota row the compiler folds).

    With ``has_scale`` the K/V blocks are int8 and dequantization happens
    HERE, per lane: scores multiply by the key scale after the QK dot
    (each lane is one (position, kv head) vector with one scale), and
    attention weights multiply by the value scale before the V dot —
    sum_l p[l] * vs[l] * v[l, :] == dot(p * vs, v). The full-precision
    page never exists; the pool's HBM read is the int8 bytes + the
    (b, pages_per_row*ps*n_kv) gathered scales (~3% of the pool).
    """
    len_ref = refs[1]
    q_ref = refs[3]
    at = 4
    if int8_qk:
        qs_ref = refs[at]  # (1, rows, 1) per-row q scales
        at += 1
    else:
        qs_ref = None
    k_refs = refs[at : at + unroll]
    v_refs = refs[at + unroll : at + 2 * unroll]
    at = at + 2 * unroll
    if has_scale:
        ks_ref, vs_ref = refs[at], refs[at + 1]
        at += 2
    else:
        ks_ref = vs_ref = None
    rest = refs[at:]
    if has_mask:
        mask_ref, o_ref, m_sc, l_sc, acc_sc = rest
    else:
        o_ref, m_sc, l_sc, acc_sc = rest
        mask_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)
    rows = q_ref.shape[1]  # qw * heads
    lanes = ps * n_kv

    @pl.when(j == 0)
    def _():
        m_sc[...] = jnp.full_like(m_sc, _MASK_FLOOR)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    length = len_ref[b]  # query t's position: length + t (t=0 incl.)
    q = q_ref[0]  # (qw*heads, hd)

    # Lane r of a flattened page holds position r // n_kv, kv head
    # r % n_kv; query row i is query offset i // heads, head i % heads,
    # served by kv head (i % heads) // group. Static over the kernel.
    lane_pos = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1) // n_kv
    lane_kv = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1) % n_kv
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    row_t = row_iota // heads
    head_kv = (row_iota % heads) // group
    head_match = lane_kv == head_kv

    m = m_sc[...]
    l = l_sc[...]
    acc = acc_sc[...]
    for u in range(unroll):
        base = (j * unroll + u) * ps
        k = k_refs[u][0, 0]  # (ps*kv, hd) — pool pre-flattened by wrapper
        v = v_refs[u][0, 0]
        if int8_qk:
            # s8 x s8 -> s32 on the MXU (v5e-native): q was quantized
            # per row by the wrapper, so the score is
            # (q_i8 . k_i8) * q_scale[row] * k_scale[lane] * sm_scale —
            # no int8->bf16 K cast anywhere in the kernel.
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32) * scale
            s = s * qs_ref[0]  # (rows, 1) broadcast
        else:
            if has_scale:
                # int8 -> q.dtype is exact (|values| <= 127); the
                # per-lane scale rides the SCORE, not a dequantized K
                # copy.
                k = k.astype(q.dtype)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (qw*heads, ps*kv)
        if has_scale:
            s = s * ks_ref[0, 0, u * lanes : (u + 1) * lanes][None, :]
        pos = base + lane_pos
        valid = jnp.logical_and(head_match, pos <= length + row_t)
        if window is not None:
            valid = jnp.logical_and(valid, pos > length + row_t - window)
        if mask_ref is not None:
            mrow = mask_ref[0, 0, u * lanes : (u + 1) * lanes]  # (ps*kv,)
            valid = jnp.logical_and(valid, mrow[None, :] != 0)
        s = jnp.where(valid, s, NEG_INF)

        # m never drops below _MASK_FLOOR, so masked lanes (s = NEG_INF)
        # give p = exp(NEG_INF - m) = 0 exactly, in every state.
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)  # 1.0 on fully-masked steps
        p = jnp.exp(s - m_new[:, :1])  # exact 0 on masked lanes
        l = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        m = m_new
        if has_scale:
            # Fold the per-lane value scale into p (masked lanes are
            # exactly 0, so garbage scales on dead lanes are inert).
            # With int8_qk the q block is int8 — the PV dot still runs
            # in the output dtype (o_ref's), never integer.
            pv_dtype = o_ref.dtype if int8_qk else q.dtype
            vsl = vs_ref[0, 0, u * lanes : (u + 1) * lanes]
            pv = (p * vsl[None, :]).astype(pv_dtype)
            vv = v.astype(pv_dtype)
        else:
            pv = p.astype(v.dtype)
            vv = v
        acc = acc * alpha[:, :1] + jax.lax.dot_general(
            pv, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    m_sc[...] = m
    l_sc[...] = l
    acc_sc[...] = acc

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        l1 = l_sc[:, :1]
        # Position 0 is always <= length, so l > 0 for every real row;
        # the guard only protects rows a caller fully masked via kv_mask.
        safe_l = jnp.where(l1 == 0.0, 1.0, l1)
        o_ref[0] = (acc_sc[...] / safe_l).astype(o_ref.dtype)


def paged_decode_attention(
    q,
    k_pool,
    v_pool,
    page_table,
    lengths,
    *,
    layer=None,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    kv_mask: Optional[jax.Array] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    int8_qk: bool = False,
    pages_per_step: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Decode / chunk-verify attention over a paged KV pool.

    Args:
      q: (batch, n_heads, head_dim) — this step's queries, RoPE
        applied. MULTI-QUERY: (batch, qw, n_heads, head_dim) scores a
        qw-token chunk per row in ONE pass over the pool (the
        speculative-verify shape): query t of row b sits at slot
        position ``lengths[b] + t`` and sees keys at ``pos <=
        lengths[b] + t`` — the chunk's K/V must already be scattered
        into the pool. Pages still stream exactly once for all
        queries; the chunk folds into the kernel's row axis.
      k_pool, v_pool: (n_pages, page_size, n_kv_heads, head_dim) —
        physical pages, POST-scatter (the current token's K/V already
        written at position ``lengths[b]`` of row ``b``). With ``layer``
        given, the STACKED pools (n_layers, n_pages, page_size, kv, hd):
        the kernel addresses pages of layer ``layer`` directly in the
        stacked array, so the caller never materialises a per-layer
        slice (inside a scan-over-layers, slicing the pool would copy
        the entire layer — the whole point of this mode is that the
        pool is only ever touched page-by-page).
      page_table: (batch, pages_per_row) int32 — logical→physical page
        map; entries past a row's length may point anywhere live (the
        engine points them at scratch page 0) — they are never read.
      lengths: (batch,) int32 — the FIRST query's position (the current
        token for plain decode, the chunk start for multi-query); keys
        at ``pos <= lengths[b] + t`` are visible to query t
        (slot-space causality).
      layer: optional traced int32 scalar — which layer of stacked
        5-D pools to read (scalar-prefetched into the index maps).
      scale: score scale; defaults to head_dim ** -0.5.
      window: sliding window — keys further than ``window - 1`` behind
        the current position are hidden.
      kv_mask: optional (batch, pages_per_row * page_size) bool — extra
        per-position visibility AND'ed onto the causal mask.
      k_scale, v_scale: per-(position, kv head) f32 dequantization
        scales for an int8 pool — (n_pages, page_size, n_kv) or,
        stacked, (n_layers, n_pages, page_size, n_kv), matching the
        pool layout (core.qtensor.quantize_kv). Pass both or neither;
        with them the K/V pools must be int8 and dequantization happens
        inside the kernel (see _decode_kernel). The per-layer scale
        gather below is the MEASURED-best design at the production
        page-256 grain: an engine-wide all-layer pre-gather into
        slot-logical layout was built and ran SLOWER (transpose +
        per-write mirror materialisation; see
        models/transformer.py _paged_block_attention).
      int8_qk: quantize q per ROW (scale = max|q|/127) and run the QK
        score as an s8 x s8 -> s32 MXU dot, the per-row q scale applied
        after — removes the kernel's int8->bf16 K cast entirely.
        Requires an int8 pool (k_scale/v_scale). Adds q-rounding error
        ~1/127 relative per component on top of the pool's own
        quantization; exactness tests pin the bound and engine top-1
        agreement. Off by default at this seam (the tight
        kernel==dequant-reference parity tests use bf16 QK); the model
        layer opts in for int8 pools (TransformerConfig.int8_qk_dot).
      pages_per_step: pages fetched per grid step (DMA/compute grain).
        Default: adaptive, ~512 tokens per grid group — grid-step fixed
        costs (DMA issue, scalar work, MXU ramp on tiny dots) dominate
        the kernel below that grain. Measured at 1.2B/16 slots/1900-tok
        prompts on v5e: page 64 x unroll 4 ran the kernel at ~3.4x its
        compulsory traffic (60% of the decode step); page 256 x
        unroll 2 cut the whole step 8.7 -> 6.8 ms (bf16).
      interpret: force pallas interpret mode; defaults to interpret
        unless running on TPU (CPU tests exercise this same kernel).

    Returns:
      (batch, n_heads, head_dim) — or (batch, qw, n_heads, head_dim)
      for a 4-D q — in q.dtype.
    """
    if q.ndim == 4:
        b, qw, n_heads, hd = q.shape
        chunked = True
    else:
        b, n_heads, hd = q.shape
        qw, chunked = 1, False
    rows = qw * n_heads
    q = q.reshape(b, rows, hd)
    out_dtype = q.dtype
    if int8_qk:
        if k_scale is None:
            raise ValueError("int8_qk needs an int8 pool (k_scale/v_scale)")
        qf = q.astype(jnp.float32)
        q_scales = jnp.maximum(
            jnp.max(jnp.abs(qf), axis=-1, keepdims=True), 1e-30
        ) / 127.0  # (b, rows, 1)
        q = jnp.round(qf / q_scales).astype(jnp.int8)
    if layer is not None:
        n_layers, n_pages, ps, n_kv, _ = k_pool.shape
    else:
        n_pages, ps, n_kv, _ = k_pool.shape
    pages_per_row = page_table.shape[1]
    if n_heads % n_kv:
        raise ValueError(f"n_heads={n_heads} not divisible by kv={n_kv}")
    group = n_heads // n_kv
    scale = float(scale) if scale is not None else hd**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pages_per_step is None:
        pages_per_step = max(1, 512 // ps)
    unroll = max(1, min(pages_per_step, pages_per_row))
    n_steps = -(-pages_per_row // unroll)

    table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    # Unified layout: the single-pool call is layer 0 of a 1-layer stack
    # (a free leading-axis reshape), so one kernel serves both modes.
    li_arr = jnp.asarray(layer if layer is not None else 0, jnp.int32)[None]
    n_layers_ = n_layers if layer is not None else 1

    def _clamped_page(u, ib, j, table_ref, len_ref):
        # Clamp to the row's live page range: steps past the row's
        # length (and, with a sliding window, steps wholly before
        # the window) repeat a neighbouring block index, which
        # Mosaic never re-fetches — per-row DMA is O(live pages)
        # (O(window) pages when windowed), not O(pages_per_row).
        # Multi-query: the last chunk query sits at length + qw - 1
        # (capacity-clamped — overshooting chunk tails were scattered
        # to scratch and are masked by the caller/causality).
        jl = j * unroll + u
        hi = jnp.minimum(
            (len_ref[ib] + (qw - 1)) // ps, pages_per_row - 1
        )
        if window is not None:
            lo = jnp.maximum(len_ref[ib] - (window - 1), 0) // ps
            jl = jnp.maximum(jl, lo)
        return table_ref[ib, jnp.minimum(jl, hi)]

    def page_of(u):
        def index(ib, j, table_ref, len_ref, li_ref):
            return (li_ref[0], _clamped_page(u, ib, j, table_ref, len_ref), 0, 0)

        return index


    # Flatten (ps, kv) into the sublane axis OUTSIDE the kernel — the
    # trailing (kv, hd) dims are already one native (8, 128) tile, so
    # this is a free reinterpretation for XLA, and the kernel's blocks
    # arrive in their compute layout with no in-kernel relayout.
    k_flat = k_pool.reshape(n_layers_, n_pages, ps * n_kv, hd)
    v_flat = v_pool.reshape(n_layers_, n_pages, ps * n_kv, hd)
    kv_spec = [
        pl.BlockSpec((1, 1, ps * n_kv, hd), page_of(u))
        for u in range(unroll)
    ]
    in_specs = (
        [pl.BlockSpec((1, rows, hd), lambda ib, j, t, l, li: (ib, 0, 0))]
        + (
            [pl.BlockSpec((1, rows, 1), lambda ib, j, t, l, li: (ib, 0, 0))]
            if int8_qk else []
        )
        + kv_spec
        + kv_spec
    )
    inputs = (
        [q]
        + ([q_scales] if int8_qk else [])
        + [k_flat] * unroll
        + [v_flat] * unroll
    )
    has_scale = k_scale is not None
    if has_scale != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if has_scale:
        if k_pool.dtype != jnp.int8:
            raise ValueError(
                f"k_scale/v_scale imply an int8 pool, got {k_pool.dtype}"
            )

        # Gather the live scales into each row's LOGICAL layout OUTSIDE
        # the kernel and stream them like the mask (one (1, 1, U*ps*kv)
        # block per grid step). Feeding pool-layout scales as per-page
        # blocks measured SLOWER than bf16 KV: 2 extra DMAs per PAGE
        # (vs per grid step) at ~1 KB each — decode's per-step compute
        # is tiny, so the DMA issue count is the cost that matters. The
        # gather itself is ~3% of the pool's bytes (f32 per (pos, kv)).
        def gather_scales(s_pool):
            # Keep the pool's scale dtype through the gather AND the
            # streamed blocks: bf16 scale pools (round 5) halve both
            # the per-layer gather bytes and the two per-grid-step
            # scale DMAs — the measured cost of the int8-KV format.
            # The kernel's multiplies promote to f32 on use.
            s5 = s_pool.reshape(n_layers_, n_pages, ps, n_kv)
            g = s5[li_arr[0], table]  # (b, pages_per_row, ps, n_kv)
            flat = g.reshape(b, -1)
            pad = n_steps * unroll * ps * n_kv - flat.shape[1]
            if pad:
                flat = jnp.pad(flat, ((0, 0), (0, pad)))
            return flat[:, None, :]

        scale_spec = pl.BlockSpec(
            (1, 1, unroll * ps * n_kv),
            lambda ib, j, t, l, li: (ib, 0, j),
        )
        in_specs += [scale_spec, scale_spec]
        inputs += [gather_scales(k_scale), gather_scales(v_scale)]
    has_mask = kv_mask is not None
    if has_mask:
        # Pre-expand to lane space: lane r of a flattened page = position
        # r // n_kv, so repeat each position's bit n_kv times. Padded to
        # the grid (pad bits are 0 = invalid; causality hides them too).
        m = jnp.repeat(kv_mask.astype(jnp.int32), n_kv, axis=1)
        pad = n_steps * unroll * ps * n_kv - m.shape[1]
        if pad:
            m = jnp.pad(m, ((0, 0), (0, pad)))
        inputs.append(m[:, None, :])
        in_specs.append(
            pl.BlockSpec(
                (1, 1, unroll * ps * n_kv),
                lambda ib, j, t, l, li: (ib, 0, j),
            )
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_steps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, rows, hd), lambda ib, j, t, l, li: (ib, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, _LANES), jnp.float32),  # running max
            pltpu.VMEM((rows, _LANES), jnp.float32),  # normaliser
            pltpu.VMEM((rows, hd), jnp.float32),      # accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale, window, n_kv, group, unroll, ps,
            has_mask, has_scale, n_heads, int8_qk,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, hd), out_dtype),
        interpret=interpret,
    )(table, lengths, li_arr, *inputs)
    return out.reshape(b, qw, n_heads, hd) if chunked else out
