"""Pallas TPU flash attention (blocked, causal, GQA, segment-aware).

Memory-bound attention never materialises the (S, S) score matrix in HBM:
the forward streams K/V blocks through VMEM with an online softmax
(running max ``m``, normaliser ``l``, and f32 accumulator), and the
backward recomputes probabilities from the saved logsumexp instead of
storing them — the flash-attention recurrence, laid out for the TPU:

  * grid order puts the KV-block dimension innermost, so the running
    (m, l, acc) state lives in VMEM scratch across KV steps and the
    output block is written exactly once, at the last step;
  * every contraction is a ``dot_general`` with
    ``preferred_element_type=f32`` — scores and accumulators stay f32
    while the MXU consumes bf16 operands;
  * GQA never materialises repeated K/V heads: the K/V BlockSpec index
    map folds the query head onto its KV head (``h // group``), and the
    dK/dV kernel accumulates over the group with an extra inner grid
    dimension instead of an HBM-sized intermediate;
  * causal masking skips fully-masked KV blocks via ``pl.when`` on the
    block-level predicate, so the skipped grid steps do no FLOPs;
  * Gemma-2 tanh logit soft-capping is a per-tile VPU elementwise on
    the block scores BEFORE the mask and the (m, l, acc) fold — the
    recurrence is unchanged, the saved logsumexp is over capped
    scores, and the backward multiplies ds by the sech^2 term
    (docs/attention_kernels.md).

Layout contract matches ops.attention.dot_product_attention:
q (b, sq, h, d); k/v (b, skv, h_kv, d); queries end-aligned when
sq < skv. Sequence lengths are padded to block multiples internally;
padded KV columns are masked with finite NEG_INF (never -inf: a fully
masked row would then produce NaN via (-inf) - (-inf)).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from shifu_tpu.ops.attention import NEG_INF

# Lane-replicated scratch width for the running max / normaliser. 128 is
# the TPU lane count; replicating the per-row scalars across lanes keeps
# every scratch op a plain (sublane, lane) vector op.
_LANES = 128


@dataclasses.dataclass(frozen=True)
class FlashConfig:
    causal: bool
    scale: float
    block_q: int
    block_k: int
    interpret: bool
    window: "Optional[int]" = None  # sliding window (causal only)
    # Gemma-2 attention-logit soft-capping: block scores become
    # cap * tanh(scores / cap) BEFORE the mask and the online-softmax
    # accumulation — a pure per-tile VPU elementwise, so the recurrence
    # (m, l, acc) is untouched and the saved logsumexp is over CAPPED
    # scores. The backward recomputes the cap and multiplies ds by the
    # sech^2 term 1 - tanh^2 (see _recompute_p).
    softcap: "Optional[float]" = None
    # Force the restricted (windowed) grid even when the span heuristic
    # would keep the full grid — the w << s lever: with a LARGER KV
    # block each query tile visits a short contiguous span of big
    # blocks, so both the grid-step count and the DMA volume drop to
    # O(S * window) where the full grid still fetched O(S^2) bytes and
    # burned a grid step per skipped block (pl.when skips FLOPs, not
    # the BlockSpec's DMA). See flash_attention(window_block_k=...).
    force_window_grid: bool = False


def _pad_to(x, multiple: int, axis: int):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _restricted_grid(window, b_self, b_other, n_blocks, shift,
                     force=False):
    """(n_grid, base_fn) for a windowed-causal restricted grid.

    A tile of ``b_self`` rows visits a contiguous span of ``b_other``-sized
    blocks; ``base_fn(i)`` is the first (unclamped) visible block for tile
    ``i`` and ``shift`` the column/row offset entering the bound. Returns
    base_fn=None when the span isn't a clear win (the iq-dependent index
    maps break Mosaic's affine prefetching, costing ~2x per grid step on
    v5e) — callers then keep the full grid with in-kernel skipping.

    ``force`` (the w << s lever, ``flash_attention(window_block_k=...)``):
    take the restricted grid whenever it shrinks the grid at all — the
    caller has already sized ``b_other`` LARGE so the prefetch penalty
    amortises over few, fat grid steps while the DMA volume drops from
    O(S^2) to O(S * window).
    """
    span = (window + b_self - 2) // b_other + 2
    if span >= n_blocks or (not force and span > n_blocks // 4):
        return n_blocks, None

    def base(i, _bs=b_self, _bo=b_other, _shift=shift):
        return jnp.maximum((i * _bs + _shift) // _bo, 0)

    return span, base



def _mask_for(rows0, cols0, bq, bk, kv_len, offset, causal, qs, ks,
              window=None):
    """Boolean (bq, bk) tile mask. rows0/cols0: global tile origins.

    ``qs`` is a (bq, 1) column of query segment ids and ``ks`` a (1, bk)
    row of KV segment ids — pre-oriented by the wrapper so the compare is
    a pure broadcast with no in-kernel transpose (sublane<->lane
    relayouts are what Mosaic is worst at).
    """
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + rows0
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + cols0
    mask = cols < kv_len  # KV padding
    if causal:
        mask = jnp.logical_and(mask, cols <= rows + offset)
        if window is not None:
            mask = jnp.logical_and(mask, cols > rows + offset - window)
    if qs is not None:
        mask = jnp.logical_and(mask, qs == ks)
    return mask


def _dot(a, b, *, trans_a=False, trans_b=False):
    """f32-accumulated matmul on possibly-bf16 operands."""
    ca = 0 if trans_a else 1
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())), preferred_element_type=jnp.float32
    )


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(cfg: FlashConfig, kv_len, offset, n_k_grid, n_k, has_segs,
                kv_base, *refs):
    if has_segs:
        q_ref, k_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    jk = pl.program_id(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    # Windowed grids iterate a RESTRICTED set of KV blocks per query tile;
    # kv_base maps (iq, jk) to the unclamped global KV block index.
    jkb = jk if kv_base is None else kv_base(iq) + jk

    @pl.when(jk == 0)
    def _():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    run = jkb * bk < kv_len
    if kv_base is not None:
        run = jnp.logical_and(run, jkb <= n_k - 1)  # clamped duplicates
    if cfg.causal:
        run = jnp.logical_and(run, jkb * bk <= iq * bq + (bq - 1) + offset)
        if cfg.window is not None:
            # Skip KV blocks wholly left of the first query row's window.
            run = jnp.logical_and(
                run,
                jkb * bk + (bk - 1) > iq * bq + offset - cfg.window,
            )

    @pl.when(run)
    def _():
        q = q_ref[0, 0]  # (bq, d)
        k = k_ref[0, 0]  # (bk, d)
        v = v_ref[0, 0]
        s = _dot(q, k, trans_b=True) * cfg.scale
        if cfg.softcap is not None:
            # Cap BEFORE the mask (the masked NEG_INF must stay
            # un-capped so masked columns still vanish under exp).
            s = jnp.tanh(s * (1.0 / cfg.softcap)) * cfg.softcap
        mask = _mask_for(
            iq * bq, jkb * bk, bq, bk, kv_len, offset, cfg.causal,
            qs_ref[0] if has_segs else None,
            ks_ref[0] if has_segs else None,
            window=cfg.window,
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[...]                       # (bq, LANES) lane-replicated
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)          # rescale factor, <= 1
        p = jnp.exp(s - m_new[:, :1])            # (bq, bk) f32
        l_sc[...] = alpha * l_sc[...] + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        acc_sc[...] = acc_sc[...] * alpha[:, :1] + _dot(p.astype(v.dtype), v)

    @pl.when(jk == n_k_grid - 1)
    def _():
        l = l_sc[:, :1]
        # Fully-masked rows (query padding) have l == 0; emit zeros for
        # them instead of 0/0 NaN — the wrapper slices them off anyway.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_sc[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[:, :1] + jnp.log(safe_l)


def _flash_forward(q, k, v, segment_ids, cfg: FlashConfig):
    """q (b, h, sq, d); k/v (b, h_kv, skv, d). Returns (o, lse)."""
    b, h, sq, d = q.shape
    _, h_kv, skv, _ = k.shape
    group = h // h_kv
    bq = min(cfg.block_q, sq)
    bk = min(cfg.block_k, skv)
    offset = skv - sq  # end-aligned queries (matches the XLA path)

    qp = _pad_to(q, bq, 2)
    kp = _pad_to(k, bk, 2)
    vp = _pad_to(v, bk, 2)
    n_q = qp.shape[2] // bq
    n_k = kp.shape[2] // bk

    # Windowed causal attention visits only the KV blocks that can fall
    # inside ANY query row of the tile: a contiguous span of
    # ceil((window + bq)/bk) + 1 blocks starting at the window's left
    # edge. The grid shrinks accordingly — DMA and FLOPs become
    # O(S * window), not O(S^2).
    kv_base = None
    n_k_grid = n_k
    if cfg.causal and cfg.window is not None:
        n_k_grid, kv_base = _restricted_grid(
            cfg.window, bq, bk, n_k, offset - cfg.window + 1,
            force=cfg.force_window_grid,
        )

    def kv_block(iq, jk):
        base = jk if kv_base is None else kv_base(iq) + jk
        return jnp.minimum(base, n_k - 1)  # clamp; kernel skips duplicates

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
        pl.BlockSpec(
            (1, 1, bk, d),
            lambda ib, ih, iq, jk: (ib, ih // group, kv_block(iq, jk), 0),
        ),
        pl.BlockSpec(
            (1, 1, bk, d),
            lambda ib, ih, iq, jk: (ib, ih // group, kv_block(iq, jk), 0),
        ),
    ]
    inputs = [qp, kp, vp]
    has_segs = segment_ids is not None
    if has_segs:
        # Mosaic tiling wants the last two block dims (8, 128)-aligned or
        # full-size; orienting q segs as a (sq, 1) column and kv segs as a
        # (1, skv) row satisfies that AND makes the in-kernel compare a
        # plain broadcast.
        seg = segment_ids.astype(jnp.int32)
        inputs += [
            _pad_to(seg[:, :, None], bq, 1),
            _pad_to(seg[:, None, :], bk, 2),
        ]
        in_specs += [
            pl.BlockSpec((1, bq, 1), lambda ib, ih, iq, jk: (ib, iq, 0)),
            pl.BlockSpec(
                (1, 1, bk),
                lambda ib, ih, iq, jk: (ib, 0, kv_block(iq, jk)),
            ),
        ]

    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, cfg, skv, offset, n_k_grid, n_k, has_segs, kv_base
        ),
        grid=(b, h, n_q, n_k_grid),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
            pl.BlockSpec(
                (1, 1, bq, 1), lambda ib, ih, iq, jk: (ib, ih, iq, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_q * bq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, n_q * bq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, _LANES), jnp.float32),  # normaliser l
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
        interpret=cfg.interpret,
    )(*inputs)
    return o[:, :, :sq], lse[:, :, :sq]


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _recompute_p(cfg, q, k, lse_row, mask):
    """Rebuild the probability tile from saved logsumexp. Returns
    (p, dcap): p the (bq, bk) f32 probabilities and dcap the softcap
    chain-rule factor d(capped)/d(raw) = 1 - tanh^2 (None when no
    softcap) — ``ds_raw = ds_capped * dcap`` is the only extra term
    the capped backward needs (the lse was saved over CAPPED scores,
    so p itself rebuilds through the same cap as the forward)."""
    s = _dot(q, k, trans_b=True) * cfg.scale
    dcap = None
    if cfg.softcap is not None:
        t = jnp.tanh(s * (1.0 / cfg.softcap))
        s = t * cfg.softcap
        dcap = 1.0 - t * t
    s = jnp.where(mask, s, NEG_INF)
    return jnp.exp(s - lse_row), dcap


def _dq_kernel(cfg, kv_len, offset, n_k_grid, n_k, has_segs, kv_base, *refs):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, dq_sc) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    jk = pl.program_id(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    jkb = jk if kv_base is None else kv_base(iq) + jk

    @pl.when(jk == 0)
    def _():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    run = jkb * bk < kv_len
    if kv_base is not None:
        run = jnp.logical_and(run, jkb <= n_k - 1)
    if cfg.causal:
        run = jnp.logical_and(run, jkb * bk <= iq * bq + (bq - 1) + offset)
        if cfg.window is not None:
            run = jnp.logical_and(
                run,
                jkb * bk + (bk - 1) > iq * bq + offset - cfg.window,
            )

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        mask = _mask_for(
            iq * bq, jkb * bk, bq, bk, kv_len, offset, cfg.causal,
            qs_ref[0] if has_segs else None,
            ks_ref[0] if has_segs else None,
            window=cfg.window,
        )
        lse_row = lse_ref[0, 0]                 # (bq, 1)
        p, dcap = _recompute_p(cfg, q, k, lse_row, mask)
        dp = _dot(do, v, trans_b=True)          # (bq, bk) f32
        ds = p * (dp - delta_ref[0, 0])
        if dcap is not None:
            ds = ds * dcap
        dq_sc[...] += _dot(ds.astype(k.dtype), k) * cfg.scale

    @pl.when(jk == n_k_grid - 1)
    def _():
        dq_ref[0, 0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(cfg, kv_len, offset, group, n_q_grid, n_q, has_segs,
                q_base, *refs):
    if has_segs:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_sc, dv_sc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_sc, dv_sc) = refs
        qs_ref = ks_ref = None
    jk = pl.program_id(2)
    g = pl.program_id(3)
    iq = pl.program_id(4)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    iqb = iq if q_base is None else q_base(jk) + iq

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    # Padded KV columns are masked to p == 0, so only the causal predicate
    # can skip a block here.
    run = True
    if q_base is not None:
        run = iqb <= n_q - 1  # clamped duplicates
    if cfg.causal:
        run = jnp.logical_and(
            run, jk * bk <= iqb * bq + (bq - 1) + offset
        )
        if cfg.window is not None:
            # Skip query blocks whose EVERY row's window starts after this
            # KV block ends (smallest row is iqb*bq).
            run = jnp.logical_and(
                run,
                jk * bk + (bk - 1) > iqb * bq + offset - cfg.window,
            )

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        mask = _mask_for(
            iqb * bq, jk * bk, bq, bk, kv_len, offset, cfg.causal,
            qs_ref[0] if has_segs else None,
            ks_ref[0] if has_segs else None,
            window=cfg.window,
        )
        lse_row = lse_ref[0, 0]
        p, dcap = _recompute_p(cfg, q, k, lse_row, mask)
        # Padded query rows carry do == 0 (the wrapper zero-pads the
        # cotangent), so their p rows contribute nothing below.
        dv_sc[...] += _dot(p.astype(do.dtype), do, trans_a=True)
        dp = _dot(do, v, trans_b=True)
        ds = p * (dp - delta_ref[0, 0])
        if dcap is not None:
            ds = ds * dcap
        dk_sc[...] += _dot(ds.astype(q.dtype), q, trans_a=True) * cfg.scale

    @pl.when(jnp.logical_and(g == group - 1, iq == n_q_grid - 1))
    def _():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, segment_ids, o, lse, do, cfg: FlashConfig):
    b, h, sq, d = q.shape
    _, h_kv, skv, _ = k.shape
    group = h // h_kv
    bq = min(cfg.block_q, sq)
    bk = min(cfg.block_k, skv)
    offset = skv - sq

    # delta_i = sum_d dO_i * O_i  — one cheap fused elementwise reduce; no
    # reason to burn a kernel on it. Trailing unit dim matches lse's
    # Mosaic-friendly (bq, 1) tile orientation.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    qp = _pad_to(q, bq, 2)
    kp = _pad_to(k, bk, 2)
    vp = _pad_to(v, bk, 2)
    dop = _pad_to(do, bq, 2)
    lsep = _pad_to(lse, bq, 2)
    deltap = _pad_to(delta, bq, 2)
    n_q = qp.shape[2] // bq
    n_k = kp.shape[2] // bk

    # Restricted grids for windowed causal attention (see _flash_forward).
    kv_base = q_base = None
    n_k_grid, n_q_grid = n_k, n_q
    if cfg.causal and cfg.window is not None:
        n_k_grid, kv_base = _restricted_grid(
            cfg.window, bq, bk, n_k, offset - cfg.window + 1,
            force=cfg.force_window_grid,
        )
        # dkv iterates query tiles per KV block; first visible query row
        # for block jk is jk*bk - offset.
        n_q_grid, q_base = _restricted_grid(
            cfg.window, bk, bq, n_q, -offset,
            force=cfg.force_window_grid,
        )

    def kv_block(iq, jk):
        base = jk if kv_base is None else kv_base(iq) + jk
        return jnp.minimum(base, n_k - 1)

    def q_block(jk, iq):
        base = iq if q_base is None else q_base(jk) + iq
        return jnp.minimum(base, n_q - 1)

    has_segs = segment_ids is not None
    seg_inputs = []
    if has_segs:
        seg = segment_ids.astype(jnp.int32)
        seg_inputs = [
            _pad_to(seg[:, :, None], bq, 1),   # (b, sq, 1) query column
            _pad_to(seg[:, None, :], bk, 2),   # (b, 1, skv) KV row
        ]

    # ---- dq: grid (b, h, iq, jk), KV innermost --------------------------
    dq_in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
        pl.BlockSpec(
            (1, 1, bk, d),
            lambda ib, ih, iq, jk: (ib, ih // group, kv_block(iq, jk), 0),
        ),
        pl.BlockSpec(
            (1, 1, bk, d),
            lambda ib, ih, iq, jk: (ib, ih // group, kv_block(iq, jk), 0),
        ),
        pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, jk: (ib, ih, iq, 0)),
    ]
    if has_segs:
        dq_in_specs += [
            pl.BlockSpec((1, bq, 1), lambda ib, ih, iq, jk: (ib, iq, 0)),
            pl.BlockSpec(
                (1, 1, bk),
                lambda ib, ih, iq, jk: (ib, 0, kv_block(iq, jk)),
            ),
        ]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, cfg, skv, offset, n_k_grid, n_k, has_segs, kv_base
        ),
        grid=(b, h, n_q, n_k_grid),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, bq, d), lambda ib, ih, iq, jk: (ib, ih, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, n_q * bq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=cfg.interpret,
    )(qp, kp, vp, dop, lsep, deltap, *seg_inputs)

    # ---- dk/dv: grid (b, h_kv, jk, g, iq) — group and Q innermost so the
    # per-KV-block accumulators sum over every query head in the group and
    # every query block without an HBM-sized intermediate. ---------------
    def qhead(ib, ih, jk, g, iq):
        return (ib, ih * group + g, q_block(jk, iq), 0)

    dkv_in_specs = [
        pl.BlockSpec((1, 1, bq, d), qhead),
        pl.BlockSpec((1, 1, bk, d), lambda ib, ih, jk, g, iq: (ib, ih, jk, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda ib, ih, jk, g, iq: (ib, ih, jk, 0)),
        pl.BlockSpec((1, 1, bq, d), qhead),
        pl.BlockSpec((1, 1, bq, 1), qhead),
        pl.BlockSpec((1, 1, bq, 1), qhead),
    ]
    if has_segs:
        dkv_in_specs += [
            pl.BlockSpec(
                (1, bq, 1),
                lambda ib, ih, jk, g, iq: (ib, q_block(jk, iq), 0),
            ),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, jk, g, iq: (ib, 0, jk)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, cfg, skv, offset, group, n_q_grid, n_q, has_segs,
            q_base,
        ),
        grid=(b, h_kv, n_k, group, n_q_grid),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec(
                (1, 1, bk, d), lambda ib, ih, jk, g, iq: (ib, ih, jk, 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda ib, ih, jk, g, iq: (ib, ih, jk, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h_kv, n_k * bk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h_kv, n_k * bk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(qp, kp, vp, dop, lsep, deltap, *seg_inputs)

    return dq[:, :, :sq], dk[:, :, :skv], dv[:, :, :skv]


# --------------------------------------------------------------------------
# custom-vjp wrapper
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, segment_ids, cfg: FlashConfig):
    o, _ = _flash_forward(q, k, v, segment_ids, cfg)
    return o


def _flash_fwd(q, k, v, segment_ids, cfg):
    o, lse = _flash_forward(q, k, v, segment_ids, cfg)
    return o, (q, k, v, segment_ids, o, lse)


def _flash_bwd(cfg, residuals, do):
    q, k, v, segment_ids, o, lse = residuals
    dq, dk, dv = _flash_backward(q, k, v, segment_ids, o, lse, do, cfg)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
    window_block_k: Optional[int] = None,
    softcap: Optional[float] = None,
    variant=None,
):
    """Flash attention with the dot_product_attention layout/semantics.

    Args:
      q: (batch, q_len, num_heads, head_dim).
      k, v: (batch, kv_len, num_kv_heads, head_dim); num_heads must divide
        evenly over num_kv_heads.
      causal: causal mask, queries end-aligned to the KV axis.
      scale: score scale; defaults to head_dim ** -0.5.
      segment_ids: optional (batch, seq) int segments for packed sequences;
        requires q_len == kv_len (same contract as the XLA path).
      block_q, block_k: EXPLICIT tile-size overrides (clamped to the
        sequence lengths) — the manual lever for tests and shape
        experiments. Default (None): the kernel-variant registry
        resolves them (ops.pallas.registry) — ``v0`` keeps the
        measured-best 1024/1024 (v5e: ~4% over 512/1024; smaller
        tiles lose up to 15%), and an active tune table
        (``shifu_tpu tune`` / ``--tune-table``) may pick a measured
        per-shape-class variant instead.
      interpret: force pallas interpret mode; default: interpret unless
        running on TPU (so CPU tests exercise the same kernel code).
      window_block_k: the small-window (w << s) grid lever. A KV block
        size used TOGETHER with the FORCED restricted grid: each query
        tile visits only the short contiguous span of (large) KV blocks
        its window can touch, so grid steps and K/V DMA drop to
        O(S * window) — the full grid fetches O(S^2) bytes even when
        ``pl.when`` skips the masked blocks' FLOPs, which is what held
        the windowed long-context legs ~12 MFU points under full
        causal. Default (None): the resolved variant decides — ``v0``
        auto-engages at 2x the window (power-of-two-rounded) whenever
        ``window`` is set and the KV length is >= 4x the window (the
        PR-3 heuristic, now the registry's ``wgrid_x2`` as an
        explicit, measurable choice); pass a block size to override,
        or 0 to disable and keep the full grid with in-kernel
        skipping.
      softcap: Gemma-2 attention-logit soft-capping — block scores
        become ``softcap * tanh(scores / softcap)`` before the mask
        and the online-softmax fold (per-tile VPU elementwise; the
        saved logsumexp is over capped scores and the backward carries
        the matching ``1 - tanh^2`` term). Composes with ``window``,
        GQA and ``segment_ids``; matches the XLA path's capping.
      variant: kernel-variant override — a registry name ("v0",
        "wgrid_x2", ...) or a KernelVariant. Default (None): resolve
        via ops.pallas.registry — the active tune table's winner for
        this call's shape class, else v0. Explicit block_q / block_k /
        window_block_k kwargs override the variant's knobs field by
        field (the manual lever for tests and experiments).

    Returns:
      (batch, q_len, num_heads, head_dim) in q.dtype.
    """
    from shifu_tpu.ops.pallas import registry as _reg

    b, sq, h, d = q.shape
    _, skv, h_kv, _ = k.shape
    if h % h_kv:
        raise ValueError(f"num_heads={h} not divisible by kv={h_kv}")
    if segment_ids is not None and sq != skv:
        raise ValueError("segment_ids requires q_len == kv_len")
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    # Variant resolution (ops/pallas/registry.py): the registry owns
    # the block-shape defaults AND the PR-3 auto-window_block_k
    # heuristic (v0 reproduces both verbatim, so numerics cannot
    # drift); an active tune table swaps in the measured winner for
    # this call's shape class.
    if isinstance(variant, str):
        named = _reg.get_variant("flash", variant)
        if named is None:
            raise ValueError(f"unknown flash variant {variant!r}")
        variant = named
    if variant is None:
        variant = _reg.resolve(_reg.ShapeClass.flash(
            kv_len=skv, head_dim=d, gqa=h // h_kv, window=window,
            softcap=softcap, dtype=q.dtype,
        ))
    knobs = variant.flash_knobs(sq, skv, window)
    if knobs.get("impl") == "xla":
        # A table may route a (softcap) class to the split/XLA path,
        # but only the dot_product_attention dispatch can honor that —
        # a direct call here has already committed to the pallas
        # kernel, so run it on v0 knobs.
        knobs = _reg.get_variant("flash", "v0").flash_knobs(
            sq, skv, window
        )
    block_q = int(block_q) if block_q is not None else knobs["block_q"]
    block_k = int(block_k) if block_k is not None else knobs["block_k"]
    if window_block_k is None:
        window_block_k = knobs["window_block_k"]
    force_window_grid = False
    if window is not None and window_block_k:
        block_k = int(window_block_k)
        force_window_grid = True
    cfg = FlashConfig(
        causal=causal,
        scale=float(scale) if scale is not None else d**-0.5,
        block_q=block_q,
        block_k=block_k,
        interpret=(
            interpret
            if interpret is not None
            else jax.default_backend() != "tpu"
        ),
        window=int(window) if window is not None else None,
        force_window_grid=force_window_grid,
        softcap=float(softcap) if softcap is not None else None,
    )
    # Kernel-native layout: heads outside the sequence axis so each grid
    # step addresses one contiguous (seq_block, head_dim) tile.
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash(qt, kt, vt, segment_ids, cfg)
    return jnp.swapaxes(o, 1, 2)
