"""Pallas TPU flash attention (blocked, causal, GQA).

Placeholder until the kernel lands: raises with a clear message instead of
silently falling back, so callers never believe they got the fused path.
"""

from __future__ import annotations


def flash_attention(q, k, v, *, causal=True, scale=None, segment_ids=None):
    raise NotImplementedError(
        "pallas flash attention kernel not implemented yet; "
        "use dot_product_attention(..., impl='xla')"
    )
