"""Kernel multi-versioning: variant registry + shape-class keys.

The Pallas fast path used to carry its tuning knobs as scattered
kwargs and inline heuristics (``block_q=1024`` measured by eye,
the PR-3 ``window_block_k`` auto-rule buried in ``flash_attention``).
Following *Autocomp* (arXiv:2505.18574) and *A Few Fit Most:
multi-versioning SGEMM* (arXiv:2507.15277), variant selection is a
first-class axis instead:

  * a :class:`ShapeClass` canonically keys the shapes a kernel is
    launched with — (seq bucket, head_dim, GQA ratio, window, softcap,
    dtype) for flash attention, (seq bucket, dim, experts, top_k,
    dtype) for MoE dispatch;
  * a :class:`KernelVariant` names one concrete configuration of a
    kernel family (block shapes, grid layout incl. the forced-window
    grid, fused-vs-split softcap, grouped-vs-einsum MoE dispatch);
    ``v0`` of each family IS the pre-registry default, resolved
    bit-identically, so introducing the registry cannot drift
    numerics;
  * :func:`resolve` maps a shape class to the variant to run: the
    ACTIVE TUNE TABLE's winner when one is loaded (``use_table`` /
    ``--tune-table``; a versioned artifact written by ``shifu_tpu
    tune`` — shifu_tpu.tune), else ``v0``. Every resolution is
    recorded (``shifu_kernel_variant_selected_total{shape_class,
    variant}`` on the global obs registry + an in-module tally served
    by ``/statz``'s ``kernels`` block), so production traffic shows
    which variants actually run.

Parity contract (pinned by tests/test_kernel_variants.py): every
registered variant computes the same attention/MoE function as ``v0``.
How exact "same" is follows from what the variant changes —

  * same effective ``block_k`` (grid layout / ``block_q`` changes
    only): the per-row online-softmax fold partition is untouched, so
    the FORWARD is bit-identical (skipped fully-masked blocks
    contribute exact zeros and identity rescales);
  * same ``block_q`` AND ``block_k``: gradients are bit-identical too
    (the dk/dv accumulation partition is per-query-block);
  * a different block partition (or the split-softcap XLA route)
    reorders f32 accumulation — parity holds to ULP-level tolerance,
    same as the repo's established flash-vs-XLA oracle contract.

``resolve`` runs at TRACE time (inside jit), so selection is free on
the hot path — a chosen variant is baked into the compiled program.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
from typing import Dict, Optional, Tuple

# -------------------------------------------------------------------------
# shape classes
# -------------------------------------------------------------------------

_DTYPE_SHORT = {
    "float32": "f32",
    "bfloat16": "bf16",
    "float16": "f16",
    "float64": "f64",
}


def _pow2_ge(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def seq_bucket(seq_len: int) -> int:
    """Canonical sequence bucket: next power of two, floored at 128."""
    return _pow2_ge(max(int(seq_len), 128))


def canonical_dtype(dtype) -> str:
    try:
        import numpy as np

        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", str(dtype))
    return _DTYPE_SHORT.get(name, name)


@dataclasses.dataclass(frozen=True)
class ShapeClass:
    """Canonical key for "shapes that should share a tuning decision".

    ``fields`` is an ordered tuple of (name, value) pairs; ``token`` is
    the canonical string form used as the tune-table key and the
    ``shape_class`` metric label. Exact sequence lengths are bucketed
    to powers of two (a winner for s=8192 serves s=7000 too); window
    widths and head dims are config constants and stay exact.
    """

    kind: str  # kernel family: "flash" | "moe"
    fields: Tuple[Tuple[str, object], ...]

    @classmethod
    def flash(cls, *, kv_len: int, head_dim: int, gqa: int,
              window: Optional[int], softcap: Optional[float], dtype):
        return cls("flash", (
            ("sb", seq_bucket(kv_len)),
            ("d", int(head_dim)),
            ("g", int(gqa)),
            ("w", int(window) if window else 0),
            ("c", 1 if softcap else 0),
            ("dt", canonical_dtype(dtype)),
        ))

    @classmethod
    def moe(cls, *, seq_len: int, dim: int, experts: int, top_k: int,
            dtype):
        return cls("moe", (
            ("sb", seq_bucket(seq_len)),
            ("d", int(dim)),
            ("e", int(experts)),
            ("k", int(top_k)),
            ("dt", canonical_dtype(dtype)),
        ))

    def get(self, name: str):
        for n, v in self.fields:
            if n == name:
                return v
        return None

    @property
    def token(self) -> str:
        return self.kind + ":" + ":".join(
            f"{n}{v}" for n, v in self.fields
        )

    @classmethod
    def parse(cls, token: str) -> "ShapeClass":
        """Inverse of ``token`` (used to validate tune-table keys)."""
        parts = token.split(":")
        kind = parts[0]
        names = {
            "flash": ("sb", "d", "g", "w", "c", "dt"),
            "moe": ("sb", "d", "e", "k", "dt"),
        }.get(kind)
        if names is None or len(parts) != len(names) + 1:
            raise ValueError(f"unparsable shape-class token: {token!r}")
        fields = []
        for name, part in zip(names, parts[1:]):
            if not part.startswith(name):
                raise ValueError(
                    f"shape-class token {token!r}: expected field "
                    f"{name!r}, got {part!r}"
                )
            raw = part[len(name):]
            try:
                fields.append((name, raw if name == "dt" else int(raw)))
            except ValueError:
                raise ValueError(
                    f"unparsable shape-class token: {token!r} "
                    f"(field {name!r} = {raw!r})"
                ) from None
        return cls(kind, tuple(fields))


# -------------------------------------------------------------------------
# variants
# -------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelVariant:
    """One named configuration of a kernel family.

    ``params`` (ordered (name, value) pairs; dict view via :attr:`p`)
    are family-specific knobs — flash: ``block_q``/``block_k`` (absent
    = the v0 default), ``window_block_k`` ("auto" = the PR-3
    heuristic, 0 = full grid with in-kernel skipping, ("mult", m) =
    FORCE the restricted window grid at m x pow2(window) KV blocks),
    ``impl`` ("xla" = the split-softcap route through the XLA oracle
    path); moe: ``impl`` ("grouped" | "einsum").
    """

    kind: str
    name: str
    params: Tuple[Tuple[str, object], ...] = ()
    doc: str = ""

    @property
    def p(self) -> Dict[str, object]:
        return dict(self.params)

    # -- applicability ----------------------------------------------------
    def applies(self, sc: ShapeClass) -> bool:
        if sc.kind != self.kind:
            return False
        p = self.p
        if self.kind == "flash":
            window = sc.get("w") or 0
            sb = sc.get("sb")
            wbk = p.get("window_block_k")
            if isinstance(wbk, tuple):  # forced window grid
                if not window:
                    return False
                # A forced span must actually shrink the grid: the
                # 2-block window span has to cover at most half the
                # (bucketed) KV axis or the restricted grid degenerates
                # into a coarser full grid.
                if 2 * wbk[1] * _pow2_ge(window) > sb // 2:
                    return False
            elif wbk == 0 and not window:
                return False  # full-grid opt-out is a no-op w/o window
            if p.get("impl") == "xla":
                # The split route materialises (S, S) scores — keep it
                # off classes where that matrix stops fitting.
                return bool(sc.get("c")) and sb <= 4096
            # Block-shape deltas are no-ops when the bucket already
            # clamps every candidate to the same size.
            for knob, dflt in (("block_q", 1024), ("block_k", 1024)):
                if knob in p and min(p[knob], sb) == min(dflt, sb):
                    return False
        return True

    # -- flash knob resolution -------------------------------------------
    def flash_knobs(self, sq: int, skv: int,
                    window: Optional[int]) -> Dict[str, object]:
        """Resolve this variant's concrete kernel knobs for REAL call
        shapes (not the bucketed class — v0's auto heuristic keys off
        the exact kv length, and resolution must reproduce the
        pre-registry behavior bit-for-bit)."""
        p = self.p
        if p.get("impl") == "xla":
            return {"impl": "xla"}
        out: Dict[str, object] = {
            "impl": "flash",
            "block_q": int(p.get("block_q", 1024)),
            "block_k": int(p.get("block_k", 1024)),
            "window_block_k": None,
        }
        wbk = p.get("window_block_k", "auto")
        if window:
            if wbk == "auto":
                # The PR-3 heuristic, verbatim: 2x-window pow2 KV
                # blocks whenever w << s (skv >= 4*window) and the
                # 2-block span still covers at most half the KV axis.
                if skv >= 4 * window:
                    cand = _pow2_ge(2 * window)
                    if 2 * cand <= skv // 2:
                        out["window_block_k"] = cand
            elif wbk == 0:
                out["window_block_k"] = 0
            elif isinstance(wbk, tuple) and wbk[0] == "mult":
                out["window_block_k"] = wbk[1] * _pow2_ge(window)
        return out


def _v(kind, name, doc, **params):
    return KernelVariant(kind, name, tuple(sorted(params.items())), doc)


# v0 of each family IS the pre-registry default — resolution reproduces
# the old inline behavior exactly, so numerics cannot drift.
FLASH_VARIANTS = (
    _v("flash", "v0",
       "default: bq=bk=1024, PR-3 auto window_block_k heuristic"),
    _v("flash", "bq_half", "half-height query tiles (fwd bit-exact)",
       block_q=512),
    _v("flash", "bk_half", "half-width KV blocks", block_k=512),
    _v("flash", "bqk_half", "both tiles halved", block_q=512,
       block_k=512),
    _v("flash", "full_grid",
       "full causal grid with in-kernel window skipping (the PR-3 "
       "lever disabled)", window_block_k=0),
    _v("flash", "wgrid_x1",
       "forced restricted grid, KV block = pow2(window)",
       window_block_k=("mult", 1)),
    _v("flash", "wgrid_x2",
       "forced restricted grid, KV block = 2*pow2(window) (the PR-3 "
       "auto heuristic as an explicit, measured choice)",
       window_block_k=("mult", 2)),
    _v("flash", "wgrid_x4",
       "forced restricted grid, KV block = 4*pow2(window)",
       window_block_k=("mult", 4)),
    _v("flash", "xla_split",
       "split softcap: route to the XLA path (cap on materialised "
       "scores) — can win at short sequences", impl="xla"),
)

MOE_VARIANTS = (
    _v("moe", "v0", "grouped sorted dispatch (PR-3 default)",
       impl="grouped"),
    _v("moe", "einsum",
       "dense one-hot dispatch/combine einsums (the GShard oracle — "
       "bit-identical routing; can win when E*C is tiny)",
       impl="einsum"),
)

VARIANTS: Dict[str, Tuple[KernelVariant, ...]] = {
    "flash": FLASH_VARIANTS,
    "moe": MOE_VARIANTS,
}


def get_variant(kind: str, name: str) -> Optional[KernelVariant]:
    for v in VARIANTS.get(kind, ()):
        if v.name == name:
            return v
    return None


def variants_for(sc: ShapeClass) -> Tuple[KernelVariant, ...]:
    """Applicable variants for a shape class, v0 first."""
    return tuple(v for v in VARIANTS.get(sc.kind, ()) if v.applies(sc))


# -------------------------------------------------------------------------
# active tune table + resolution
# -------------------------------------------------------------------------

_lock = threading.Lock()
_active_table = None  # shifu_tpu.tune.table.TuneTable | None
_active_path: Optional[str] = None
_table_cache: Dict[str, object] = {}  # path -> table | None (failed)
_selections: Dict[str, Dict[str, int]] = {}  # token -> {variant: n}
_warned: set = set()


def _warn_once(key: str, msg: str) -> None:
    with _lock:
        if key in _warned:
            return
        _warned.add(key)
    print(f"[shifu_tpu.tune] {msg}", file=sys.stderr)


def set_active_table(table, path: Optional[str] = None) -> None:
    """Install ``table`` (a tune.table.TuneTable or None) as the
    process-wide winner source for :func:`resolve`."""
    global _active_table, _active_path
    with _lock:
        _active_table = table
        _active_path = path


def active_table():
    return _active_table


def use_table(path: Optional[str]):
    """Load the tune-table artifact at ``path`` and make it active.

    Invalid artifacts NEVER break the caller: schema mismatch, corrupt
    content, or a device-kind mismatch each fall back to ``v0`` with a
    one-line warning. Loads are cached per path (the config-level
    plumbing calls this at every trace). Returns the active table (or
    None on fallback).
    """
    if not path:
        set_active_table(None, None)
        return None
    if path in _table_cache:
        table = _table_cache[path]
        if _active_path != path:
            set_active_table(table, path if table is not None else None)
        return table
    from shifu_tpu.tune.table import TuneTableError, load_table

    table = None
    try:
        table = load_table(path)
    except (OSError, TuneTableError) as e:
        _warn_once(
            f"load:{path}",
            f"tune table {path!r} unusable ({e}); running v0 defaults",
        )
    if table is not None:
        kind = _device_kind()
        if table.device_kind != kind:
            _warn_once(
                f"dev:{path}",
                f"tune table {path!r} was tuned for "
                f"{table.device_kind!r} but this process runs on "
                f"{kind!r}; running v0 defaults",
            )
            table = None
    _table_cache[path] = table
    set_active_table(table, path if table is not None else None)
    return table


def _device_kind() -> str:
    import jax

    dev = jax.devices()[0]
    return getattr(dev, "device_kind", dev.platform)


def _record_selection(sc: ShapeClass, variant: KernelVariant) -> None:
    with _lock:
        per = _selections.setdefault(sc.token, {})
        per[variant.name] = per.get(variant.name, 0) + 1
    try:
        from shifu_tpu.obs import REGISTRY

        REGISTRY.counter(
            "shifu_kernel_variant_selected_total",
            "kernel variant resolutions (trace-time) by shape class",
            ("shape_class", "variant"),
        ).labels(shape_class=sc.token, variant=variant.name).inc()
    except Exception:
        pass  # observability must never sink a kernel launch


def resolve(sc: ShapeClass, *, record: bool = True) -> KernelVariant:
    """Shape class -> the variant to run.

    The active tune table's winner when it names a registered,
    applicable variant; ``v0`` otherwise (unknown winners warn once —
    a stale table must degrade loudly-but-safely, not crash serving).
    """
    v0 = VARIANTS[sc.kind][0]
    chosen = v0
    table = _active_table
    if table is not None:
        name = table.winner(sc.token)
        if name is not None and name != v0.name:
            cand = get_variant(sc.kind, name)
            if cand is None or not cand.applies(sc):
                _warn_once(
                    f"win:{sc.token}:{name}",
                    f"tune table winner {name!r} for {sc.token} is "
                    "not a registered applicable variant; using v0",
                )
            else:
                chosen = cand
    if record:
        _record_selection(sc, chosen)
    return chosen


def selection_counts() -> Dict[str, Dict[str, int]]:
    with _lock:
        return {t: dict(c) for t, c in _selections.items()}


def kernels_status() -> dict:
    """The ``/statz`` ``kernels`` block: active table identity + the
    per-shape-class variants this process has actually selected."""
    table = _active_table
    out: dict = {
        "table": _active_path,
        "schema": None,
        "device_kind": None,
        "content_hash": None,
        "entries": {},
        "selected": selection_counts(),
    }
    if table is not None:
        out["schema"] = table.schema
        out["device_kind"] = table.device_kind
        out["content_hash"] = table.content_hash()
        out["entries"] = {
            tok: e.get("variant") for tok, e in table.entries.items()
        }
    return out


def _reset_for_tests() -> None:
    """Drop all registry state (active table, caches, tallies)."""
    global _active_table, _active_path
    with _lock:
        _active_table = None
        _active_path = None
        _table_cache.clear()
        _selections.clear()
        _warned.clear()
