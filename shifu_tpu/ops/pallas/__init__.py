"""Pallas TPU kernels + the kernel-variant registry.

``registry`` makes variant selection a first-class config axis: every
kernel family (flash attention, MoE dispatch) registers a small family
of :class:`~shifu_tpu.ops.pallas.registry.KernelVariant`'s keyed by a
canonical :class:`~shifu_tpu.ops.pallas.registry.ShapeClass`, and the
persistent autotuner (``shifu_tpu tune`` — :mod:`shifu_tpu.tune`)
picks winners by measurement into a versioned table artifact that
``--tune-table`` activates at serve/train/bench time.
"""

from shifu_tpu.ops.pallas.registry import (
    KernelVariant,
    ShapeClass,
    active_table,
    get_variant,
    kernels_status,
    resolve,
    set_active_table,
    use_table,
    variants_for,
)

__all__ = [
    "KernelVariant",
    "ShapeClass",
    "active_table",
    "get_variant",
    "kernels_status",
    "resolve",
    "set_active_table",
    "use_table",
    "variants_for",
]
