from shifu_tpu.ops.norms import rms_norm
from shifu_tpu.ops.rope import apply_rope, rope_frequencies
from shifu_tpu.ops.attention import dot_product_attention
from shifu_tpu.ops.losses import (
    fused_softmax_cross_entropy,
    softmax_cross_entropy,
)
from shifu_tpu.ops.moe import (
    moe_capacity,
    route_top_k,
    route_top_k_grouped,
)

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
    "dot_product_attention",
    "fused_softmax_cross_entropy",
    "softmax_cross_entropy",
    "moe_capacity",
    "route_top_k",
    "route_top_k_grouped",
]
