"""Metric-docs drift check: registered families vs observability docs.

``shifu_tpu obs check-docs`` (a tier-1 gate) fails when the registry
surface and ``docs/observability.md`` disagree in EITHER direction:

  * a ``shifu_*`` family registered anywhere under ``shifu_tpu/`` that
    the doc never mentions (new telemetry shipped undocumented), or
  * a family the doc names that no code registers (stale docs after a
    rename/removal).

Families are found by scanning source string literals — the registry
is built lazily per process (engines register their families in
``_obs_bind`` on construction), so a source scan is the only view that
covers every engine class without instantiating them. Dynamic names
are handled structurally:

  * an f-string family (``f"shifu_kv_tier_{k}_total"``) becomes a glob
    pattern (``shifu_kv_tier_*_total``) — documented when any doc token
    matches it, and every doc token matching it is known;
  * a literal ending in ``_`` (the ``shifu_fleet_agg_`` federation
    prefix) is a PREFIX — same matching rule;
  * doc tokens ending in ``_`` are prose prefix-mentions ("the
    ``shifu_fleet_*`` families") and are fine when any family starts
    with them.

``ALLOWLIST`` carries names exempt in both directions (bench-only
families that never register inside the package, and non-family
literals like the CLI prog name).
"""

from __future__ import annotations

import fnmatch
import os
import re
from typing import Dict, List, Set, Tuple

# Exempt in both directions: not families (CLI prog name, env-var key),
# plus bench-only families registered outside shifu_tpu/ (none today —
# add here when the bench grows one rather than documenting a family
# operators can never scrape from a server).
ALLOWLIST = frozenset({
    "shifu_tpu",
    "shifu_tpu_act_env",
})

# String literals (f-strings included) that look like metric families.
_LIT_RE = re.compile(
    r'["\'](shifu_[a-z0-9_]*(?:\{[^}"\']*\}[a-z0-9_]*)*)["\']'
)
_DOC_RE = re.compile(r"shifu_[a-z0-9_]+")


def scan_source_families(root: str) -> Dict[str, Set[str]]:
    """``shifu_*`` string literals under ``root`` (a package dir) ->
    {family_or_pattern: {relative file paths}}. ``{expr}`` segments
    become ``*``; a trailing ``_`` marks a prefix and also becomes a
    trailing ``*``."""
    out: Dict[str, Set[str]] = {}
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            rel = os.path.relpath(path, root)
            for m in _LIT_RE.finditer(text):
                name = re.sub(r"\{[^}]*\}", "*", m.group(1))
                if name in ALLOWLIST:
                    continue
                if name.endswith("_"):
                    name += "*"
                out.setdefault(name, set()).add(rel)
    return out


def scan_doc_families(doc_text: str) -> Tuple[Set[str], Set[str]]:
    """Doc ``shifu_*`` tokens -> (concrete mentions, prefix mentions).
    A token ending in ``_`` is a prose prefix-mention."""
    concrete: Set[str] = set()
    prefixes: Set[str] = set()
    for tok in _DOC_RE.findall(doc_text):
        if tok in ALLOWLIST:
            continue
        (prefixes if tok.endswith("_") else concrete).add(tok)
    return concrete, prefixes


def check_docs(package_root: str, doc_path: str) -> Tuple[bool, dict]:
    """(ok, report). ``report['undocumented']`` lists families the code
    registers that the doc never mentions; ``report['unknown']`` lists
    doc names no code registers."""
    families = scan_source_families(package_root)
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    doc_concrete, doc_prefixes = scan_doc_families(doc_text)

    undocumented: List[dict] = []
    for name in sorted(families):
        if "*" in name:
            hit = any(fnmatch.fnmatchcase(t, name) for t in doc_concrete)
        else:
            hit = name in doc_concrete or any(
                name.startswith(p) for p in doc_prefixes
            )
        if not hit:
            undocumented.append({
                "family": name,
                "registered_in": sorted(families[name]),
            })

    patterns = [n for n in families if "*" in n]
    unknown: List[str] = []
    for tok in sorted(doc_concrete):
        if tok in families:
            continue
        if any(fnmatch.fnmatchcase(tok, pat) for pat in patterns):
            continue
        unknown.append(tok)
    stale_prefixes = [
        p for p in sorted(doc_prefixes)
        if not any(f.startswith(p) for f in families)
    ]

    ok = not undocumented and not unknown and not stale_prefixes
    return ok, {
        "ok": ok,
        "families_in_code": len(families),
        "families_in_doc": len(doc_concrete),
        "undocumented": undocumented,
        "unknown": unknown + stale_prefixes,
        "doc": doc_path,
    }
