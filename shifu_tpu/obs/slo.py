"""Fleet SLO engine: per-tier burn-rate budgets over federated metrics.

The per-host :class:`~shifu_tpu.obs.watchdog.SLOWatchdog` answers "is
THIS host degraded right now"; this module answers the fleet question
the ROADMAP's autoscaling and loadgen items consume: "how fast is each
admission tier spending its error budget, and how much headroom is
left". It is evaluated at the fleet router from the same pooled
federated ``/metrics`` samples the ``shifu_fleet_agg_*`` families are
rendered from, so the SLO verdict and the dashboards literally share
one measurement.

Mechanics (the multi-window burn-rate pattern):

  * A :class:`TierBudget` declares, per admission tier (interactive /
    batch), the latency thresholds (p99 TTFT / p99 ITL) and an allowed
    error-rate, plus the ``objective`` — the fraction of requests that
    must meet each latency threshold (default 0.99, i.e. a p99
    budget: 1% of requests may exceed it).
  * The engine keeps timestamped snapshots of the pooled sample dict.
    For each evaluation window (fast ~1m, slow ~15m) it differences
    the cumulative histogram buckets / counters between now and the
    window start — histogram ``_bucket`` samples are cumulative, so
    the delta is the exact event count for the window.
  * ``burn_rate = bad_fraction / allowed_fraction``: 1.0 means the
    tier is spending its error budget exactly as fast as the budget
    allows; >1 means the budget is burning. A tier is ``burning`` when
    the FAST window burns >= 1 (responsive early warning) and
    ``breached`` when the SLOW window — with full coverage — burns
    too (sustained, not a blip). ``headroom`` is ``1 - burn`` on the
    longest window with data: the remaining budget fraction an
    autoscaler can spend before the tier breaches.

Burn rates re-export as ``shifu_slo_burn_rate{tier,window}`` gauges
(plus ``shifu_slo_headroom{tier}`` / ``shifu_slo_tier_state{tier}``)
and the full document serves on ``GET /sloz``. On an ok -> burning /
breached transition the engine fires ``on_breach`` — the router hooks
the cross-host incident-bundle capture (obs/incident.py) there.

Everything takes an injectable ``clock`` so the window math is tested
on a deterministic clock (tests/test_slo.py), the repo-wide pattern
(CircuitBreaker, FleetProber).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from shifu_tpu.obs.disttrace import AGG_PREFIX

# Canonical latency families the budgets measure (the engines' own
# tier-labelled request histograms; the router pools them under the
# federation prefix). Values are seconds on the wire.
TTFT_FAMILY = "shifu_request_ttft_seconds"
ITL_FAMILY = "shifu_request_itl_seconds"
# Router-local per-tier traffic counters (fleet/router.py registers
# them) — the error-rate budget's numerator/denominator.
REQUESTS_FAMILY = "shifu_slo_requests_total"
ERRORS_FAMILY = "shifu_slo_errors_total"

STATUS_OK = "ok"
STATUS_BURNING = "burning"
STATUS_BREACHED = "breached"
_STATE_CODES = {STATUS_OK: 0, STATUS_BURNING: 1, STATUS_BREACHED: 2}


@dataclasses.dataclass(frozen=True)
class TierBudget:
    """One admission tier's declared SLO. ``None`` budgets are not
    evaluated; ``objective`` is the fraction of requests that must meet
    each latency threshold (0.99 = p99 budgets with a 1% error
    budget)."""

    tier: str
    p99_ttft_ms: Optional[float] = None
    p99_itl_ms: Optional[float] = None
    max_error_rate: Optional[float] = None
    objective: float = 0.99

    def __post_init__(self):
        if not (0.0 < self.objective < 1.0):
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )
        if self.max_error_rate is not None and not (
            0.0 < self.max_error_rate <= 1.0
        ):
            raise ValueError(
                f"max_error_rate must be in (0, 1], got "
                f"{self.max_error_rate}"
            )
        if not self.active():
            raise ValueError(
                f"tier {self.tier!r} declares no budget (need at least "
                "one of ttft / itl / err)"
            )

    def active(self) -> bool:
        return any(
            v is not None for v in (
                self.p99_ttft_ms, self.p99_itl_ms, self.max_error_rate
            )
        )


def parse_budget_spec(spec: str) -> TierBudget:
    """CLI budget string -> :class:`TierBudget`.

    Format: ``tier:key=value,...`` with keys ``ttft`` (p99 TTFT ms),
    ``itl`` (p99 ITL ms), ``err`` (allowed error-rate fraction),
    ``objective`` (latency compliance target, default 0.99). Example:
    ``interactive:ttft=250,itl=40,err=0.01``."""
    head, sep, rest = str(spec).partition(":")
    tier = head.strip()
    if not sep or not tier:
        raise ValueError(
            f"budget spec {spec!r} must look like "
            "'tier:ttft=250,itl=40,err=0.01'"
        )
    kw: dict = {}
    keys = {
        "ttft": "p99_ttft_ms", "itl": "p99_itl_ms",
        "err": "max_error_rate", "objective": "objective",
    }
    for part in rest.split(","):
        part = part.strip()
        if not part:
            continue
        k, sep2, v = part.partition("=")
        k = k.strip()
        if not sep2 or k not in keys:
            raise ValueError(
                f"budget spec {spec!r}: unknown key {k!r} "
                f"(known: {sorted(keys)})"
            )
        try:
            kw[keys[k]] = float(v)
        except ValueError:
            raise ValueError(
                f"budget spec {spec!r}: {k}={v!r} is not a number"
            ) from None
    return TierBudget(tier=tier, **kw)


# ------------------------------------------------------- window math
def _agg(name: str) -> str:
    if name.startswith("shifu_") and not name.startswith(AGG_PREFIX):
        return AGG_PREFIX + name[len("shifu_"):]
    return name


def _bucket_acc(samples: Dict[tuple, float], family: str,
                labels: Dict[str, str]) -> Dict[float, float]:
    """Pool a family's cumulative ``_bucket`` samples (every series
    whose labels are a superset of ``labels``) -> {le_edge: count}."""
    bucket_name = _agg(family) + "_bucket"
    want = {k: str(v) for k, v in labels.items()}
    acc: Dict[float, float] = {}
    for (sname, slabels), val in samples.items():
        if sname != bucket_name:
            continue
        ld = dict(slabels)
        le = ld.pop("le", None)
        if le is None:
            continue
        if any(ld.get(k) != v for k, v in want.items()):
            continue
        edge = math.inf if le in ("+Inf", "inf") else float(le)
        acc[edge] = acc.get(edge, 0.0) + val
    return acc


def _counter_sum(samples: Dict[tuple, float], family: str,
                 labels: Dict[str, str]) -> float:
    """Sum a counter family's samples whose labels are a superset of
    ``labels`` (both the local name and its federated twin count — the
    router's own counters parse under their original names)."""
    names = {family, _agg(family)}
    want = {k: str(v) for k, v in labels.items()}
    total = 0.0
    for (sname, slabels), val in samples.items():
        if sname not in names:
            continue
        ld = dict(slabels)
        # Skip per-backend federated duplicates of a pooled series.
        if sname != family and "backend" in ld:
            continue
        if any(ld.get(k) != v for k, v in want.items()):
            continue
        total += val
    return total


def _delta_acc(now_acc: Dict[float, float],
               base_acc: Dict[float, float]) -> Dict[float, float]:
    """Windowed bucket counts: cumulative-now minus cumulative-at-
    window-start, clamped at 0 per edge (a backend restart resets its
    counters; a negative delta must not poison the fraction)."""
    out: Dict[float, float] = {}
    for edge, val in now_acc.items():
        out[edge] = max(val - base_acc.get(edge, 0.0), 0.0)
    return out


def fraction_over(acc: Dict[float, float],
                  threshold_s: float) -> Tuple[float, float]:
    """(events over ``threshold_s``, total events) from one windowed
    cumulative-bucket delta. The count at the threshold interpolates
    linearly inside the containing bucket (the same model the
    registry's quantile estimator uses); past the last finite edge
    only the ``+Inf`` remainder counts as over."""
    if not acc:
        return 0.0, 0.0
    edges = sorted(e for e in acc if e != math.inf)
    total = acc.get(math.inf, acc[edges[-1]] if edges else 0.0)
    if total <= 0.0 or not edges:
        return 0.0, max(total, 0.0)
    thr = float(threshold_s)
    prev_edge, prev_cum = 0.0, 0.0
    under = None
    for e in edges:
        cum = acc[e]
        if thr <= e:
            width = e - prev_edge
            frac = (thr - prev_edge) / width if width > 0 else 1.0
            under = prev_cum + (cum - prev_cum) * min(max(frac, 0.0), 1.0)
            break
        prev_edge, prev_cum = e, cum
    if under is None:
        # Threshold beyond the last finite edge: everything up to that
        # edge is under; only the +Inf remainder is (possibly) over.
        under = acc[edges[-1]]
    under = min(max(under, 0.0), total)
    return total - under, total


class SLOEngine:
    """Multi-window burn-rate evaluation over pooled metric snapshots.

    ``budgets`` — :class:`TierBudget` list. ``note(samples)`` records
    one timestamped snapshot of the pooled sample dict (the router
    feeds it from its federation scrape + its own registry);
    ``evaluate()`` differences the fast/slow windows, updates the
    ``shifu_slo_*`` gauges, fires ``on_breach(tier, info)`` on an
    ok -> burning/breached transition, and returns the ``/sloz``
    document. ``clock`` must be monotonic-like; tests inject a fake.
    """

    def __init__(self, budgets: List[TierBudget], *,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 900.0,
                 sample_interval_s: float = 5.0,
                 burn_threshold: float = 1.0,
                 metrics=None, flight=None,
                 clock: Callable[[], float] = time.monotonic,
                 on_breach=None):
        if not budgets:
            raise ValueError("need at least one tier budget")
        tiers = [b.tier for b in budgets]
        if len(set(tiers)) != len(tiers):
            raise ValueError(f"duplicate tier budgets: {tiers}")
        if not (0.0 < fast_window_s < slow_window_s):
            raise ValueError(
                f"need 0 < fast_window_s ({fast_window_s}) < "
                f"slow_window_s ({slow_window_s})"
            )
        from shifu_tpu import obs as _obs

        self.budgets = {b.tier: b for b in budgets}
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.sample_interval_s = float(sample_interval_s)
        self.burn_threshold = float(burn_threshold)
        self.metrics = metrics if metrics is not None else _obs.REGISTRY
        self.flight = flight if flight is not None else _obs.FLIGHT
        self.clock = clock
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._snaps: List[Tuple[float, Dict[tuple, float]]] = []
        self._state: Dict[str, str] = {t: STATUS_OK for t in self.budgets}

        reg = self.metrics
        self._g_burn = reg.gauge(
            "shifu_slo_burn_rate",
            "Error-budget burn rate per admission tier and evaluation "
            "window (1.0 = spending the budget exactly at the allowed "
            "rate; >1 = burning)", labelnames=("tier", "window"),
        )
        self._g_headroom = reg.gauge(
            "shifu_slo_headroom",
            "Remaining error-budget fraction per tier on the longest "
            "window with data (1 - burn_rate; negative = over budget)",
            labelnames=("tier",),
        )
        self._g_state = reg.gauge(
            "shifu_slo_tier_state",
            "Tier SLO state: 0 ok, 1 burning (fast window over "
            "threshold), 2 breached (slow window too, full coverage)",
            labelnames=("tier",),
        )
        self._c_breaches = reg.counter(
            "shifu_slo_tier_breaches_total",
            "ok -> burning/breached transitions per tier (each one may "
            "trigger a rate-limited incident bundle)",
            labelnames=("tier",),
        )
        for t in self.budgets:
            for w in ("fast", "slow"):
                self._g_burn.labels(tier=t, window=w)
            self._g_headroom.labels(tier=t).set(1.0)
            self._g_state.labels(tier=t).set(0.0)
            self._c_breaches.labels(tier=t)

    # ----------------------------------------------------- sampling
    def sample_due(self) -> bool:
        """Is it time for the owner to feed another snapshot? (The
        router samples lazily on /sloz and from the monitor thread.)"""
        with self._lock:
            if not self._snaps:
                return True
            return (
                self.clock() - self._snaps[-1][0]
                >= self.sample_interval_s
            )

    def note(self, samples: Dict[tuple, float]) -> None:
        """Record one pooled-sample snapshot at ``clock()`` now. Old
        snapshots prune past the slow window (one snapshot at/behind
        the window start is kept as the differencing baseline)."""
        now = self.clock()
        with self._lock:
            self._snaps.append((now, dict(samples)))
            horizon = now - self.slow_window_s
            while len(self._snaps) >= 2 and self._snaps[1][0] <= horizon:
                self._snaps.pop(0)

    @staticmethod
    def _window_base(snaps, now: float, window_s: float):
        """Newest snapshot at/behind ``now - window_s`` — or the oldest
        snapshot when coverage is still partial (reported so breached
        requires FULL slow coverage)."""
        target = now - window_s
        base = None
        for t, samples in snaps:
            if t <= target:
                base = (t, samples)
            else:
                break
        if base is None:
            base = snaps[0]
        return base

    # --------------------------------------------------- evaluation
    def _window_doc(self, budget: TierBudget, now_samples, base_samples,
                    coverage_s: float) -> dict:
        labels = {"tier": budget.tier}
        per: Dict[str, dict] = {}
        burn = 0.0
        allowed = 1.0 - budget.objective
        for key, family, thr_ms in (
            ("ttft", TTFT_FAMILY, budget.p99_ttft_ms),
            ("itl", ITL_FAMILY, budget.p99_itl_ms),
        ):
            if thr_ms is None:
                continue
            acc = _delta_acc(
                _bucket_acc(now_samples, family, labels),
                _bucket_acc(base_samples, family, labels),
            )
            bad, total = fraction_over(acc, thr_ms / 1000.0)
            b = (bad / total) / allowed if total > 0 else 0.0
            per[key] = {
                "bad": round(bad, 3), "total": round(total, 3),
                "burn_rate": round(b, 4),
            }
            burn = max(burn, b)
        if budget.max_error_rate is not None:
            total = (
                _counter_sum(now_samples, REQUESTS_FAMILY, labels)
                - _counter_sum(base_samples, REQUESTS_FAMILY, labels)
            )
            bad = (
                _counter_sum(now_samples, ERRORS_FAMILY, labels)
                - _counter_sum(base_samples, ERRORS_FAMILY, labels)
            )
            total, bad = max(total, 0.0), max(bad, 0.0)
            b = (
                (bad / total) / budget.max_error_rate
                if total > 0 else 0.0
            )
            per["error_rate"] = {
                "bad": round(bad, 3), "total": round(total, 3),
                "burn_rate": round(b, 4),
            }
            burn = max(burn, b)
        return {
            "burn_rate": round(burn, 4),
            "coverage_s": round(max(coverage_s, 0.0), 3),
            "budgets": per,
        }

    def evaluate(self) -> dict:
        """The ``GET /sloz`` document; updates gauges and fires
        ``on_breach`` on ok -> burning/breached transitions."""
        with self._lock:
            snaps = list(self._snaps)
        now = self.clock()
        tiers: Dict[str, dict] = {}
        transitions: List[Tuple[str, dict]] = []
        for tier, budget in self.budgets.items():
            if len(snaps) < 2:
                fast = slow = {
                    "burn_rate": 0.0, "coverage_s": 0.0, "budgets": {},
                }
            else:
                ft, fs = self._window_base(snaps, now, self.fast_window_s)
                st, ss = self._window_base(snaps, now, self.slow_window_s)
                latest = snaps[-1][1]
                fast = self._window_doc(budget, latest, fs, now - ft)
                slow = self._window_doc(budget, latest, ss, now - st)
            burning = fast["burn_rate"] >= self.burn_threshold
            breached = (
                burning
                and slow["burn_rate"] >= self.burn_threshold
                and slow["coverage_s"] >= self.slow_window_s
            )
            status = (
                STATUS_BREACHED if breached
                else STATUS_BURNING if burning
                else STATUS_OK
            )
            # Headroom on the longest window with data: what is left of
            # the budget before the tier breaches (negative = over).
            ref = slow if slow["coverage_s"] > 0 else fast
            headroom = round(1.0 - ref["burn_rate"], 4)
            tiers[tier] = {
                "status": status,
                "burn_rate": fast["burn_rate"],
                "headroom": headroom,
                "windows": {"fast": fast, "slow": slow},
                "budget": {
                    k: v for k, v in (
                        ("p99_ttft_ms", budget.p99_ttft_ms),
                        ("p99_itl_ms", budget.p99_itl_ms),
                        ("max_error_rate", budget.max_error_rate),
                        ("objective", budget.objective),
                    ) if v is not None
                },
            }
            self._g_burn.labels(tier=tier, window="fast").set(
                fast["burn_rate"]
            )
            self._g_burn.labels(tier=tier, window="slow").set(
                slow["burn_rate"]
            )
            self._g_headroom.labels(tier=tier).set(headroom)
            self._g_state.labels(tier=tier).set(
                float(_STATE_CODES[status])
            )
            prev = self._state[tier]
            self._state[tier] = status
            if status != STATUS_OK and prev == STATUS_OK:
                self._c_breaches.labels(tier=tier).inc()
                self.flight.record(
                    "slo_burning", tier=tier, status=status,
                    burn_rate=fast["burn_rate"], headroom=headroom,
                )
                transitions.append((tier, tiers[tier]))
            elif status == STATUS_OK and prev != STATUS_OK:
                self.flight.record("slo_recovered", tier=tier)
        doc = {
            "tiers": tiers,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "samples": len(snaps),
        }
        if self.on_breach is not None:
            for tier, info in transitions:
                try:
                    self.on_breach(tier, info)
                except Exception:  # noqa: BLE001 — forensics best-effort
                    pass
        return doc


class SLOMonitor(threading.Thread):
    """Background evaluation pump: calls ``target()`` (the router's
    ``slo_report``) every ``interval_s`` so breaches are detected — and
    incident bundles captured — without anyone polling ``/sloz``.
    Daemon thread; ``stop()`` joins it."""

    def __init__(self, target: Callable[[], object],
                 interval_s: float = 5.0):
        super().__init__(name="shifu-slo-monitor", daemon=True)
        self._target = target
        self.interval_s = float(interval_s)
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._target()
            except Exception:  # noqa: BLE001 — monitoring must survive
                pass

    def stop(self) -> None:
        self._stop.set()
        self.join(timeout=5.0)
