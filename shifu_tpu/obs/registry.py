"""Metrics registry: counters, gauges, fixed-bucket histograms, labels.

Design constraints (the serving engine thread is the hot writer):

  * ``observe``/``inc``/``set`` are a dict hit away from a couple of
    float ops — no locks on the write path. Python's GIL makes each
    individual ``+=`` effectively atomic, and every engine metric has a
    single writer (the engine thread) anyway; scrape threads only read.
    A torn read across two fields of one histogram can at worst skew a
    rate by one sample — acceptable for monitoring data.
  * Label children are pre-bound by callers (``family.labels(...)``
    once, then the child is a plain object held in a slot) so the hot
    path never touches the registry dict or builds label tuples.
  * Histograms use FIXED buckets chosen at family creation: observe is
    one bisect over a small tuple plus three adds. Quantiles are
    estimated by linear interpolation inside the containing bucket —
    the estimation error is bounded by that bucket's width (tested
    against numpy percentiles in tests/test_obs.py).

Exposition follows the Prometheus text format 0.0.4: one ``# HELP`` and
``# TYPE`` line per family, samples as ``name{label="value"} value``,
histograms as cumulative ``_bucket{le="..."}`` series plus ``_sum`` /
``_count``. Label values escape ``\\``, ``"`` and newlines per the spec.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Dict, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default (seconds): sub-millisecond dispatch costs up to
# multi-second tail prefills all land in a finite bucket.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def escape_label_value(v: str) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _escape_help(h: str) -> str:
    return str(h).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v) -> str:
    """Sample-value formatting: integral floats print as ints (half the
    bytes on count-heavy scrapes), +Inf per the exposition spec."""
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    """Monotone accumulator. Single-writer hot path; see module notes."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Point-in-time value (queue depth, active slots)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Fixed-bucket histogram. ``buckets`` are inclusive upper edges
    (Prometheus ``le`` semantics); a final +Inf bucket is implicit."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, n: int = 1) -> None:
        # bisect_left over the edge tuple: value <= edge -> that bucket
        # (inclusive upper bound, so an exact edge value counts IN its
        # edge's bucket — tested in tests/test_obs.py).
        self.counts[bisect_left(self.buckets, value)] += n
        self.sum += value * n
        self.count += n

    def quantile(self, q: float) -> Optional[float]:
        return _bucket_quantile(self.buckets, self.counts, self.count, q)


def _bucket_quantile(buckets, counts, total, q: float) -> Optional[float]:
    """Linear interpolation inside the containing bucket (error bounded
    by that bucket's width). The +Inf bucket clamps to the last finite
    edge — the honest answer when the tail escaped the chosen buckets."""
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        lo = buckets[i - 1] if i else 0.0
        hi = buckets[i] if i < len(buckets) else None
        if cum + c >= rank:
            if hi is None:  # +Inf bucket
                return buckets[-1] if buckets else None
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return buckets[-1] if buckets else None


class _Family:
    """One named metric family: kind + help + label schema + children."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self.buckets = buckets
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **labels):
        """The child for this label combination (created on first use).
        Callers bind once and hold the child — the hot path never comes
        back here."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(labels[k]) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "counter":
                        child = Counter()
                    elif self.kind == "gauge":
                        child = Gauge()
                    else:
                        child = Histogram(self.buckets)
                    self._children[key] = child
        return child

    # Zero-label convenience: family proxies to its () child.
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def dec(self, n: float = 1.0) -> None:
        self.labels().dec(n)

    def observe(self, value: float, n: int = 1) -> None:
        self.labels().observe(value, n)

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{ln}="{escape_label_value(lv)}"'
            for ln, lv in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key in sorted(self._children):
            child = self._children[key]
            if self.kind in ("counter", "gauge"):
                lines.append(
                    f"{self.name}{self._label_str(key)} "
                    f"{_fmt(child.value)}"
                )
                continue
            cum = 0
            for edge, c in zip(
                (*child.buckets, math.inf), child.counts
            ):
                cum += c
                le = 'le="' + _fmt(edge) + '"'
                lines.append(
                    f"{self.name}_bucket{self._label_str(key, le)} "
                    f"{_fmt(cum)}"
                )
            lines.append(
                f"{self.name}_sum{self._label_str(key)} {_fmt(child.sum)}"
            )
            lines.append(
                f"{self.name}_count{self._label_str(key)} "
                f"{_fmt(child.count)}"
            )
        return "\n".join(lines)

    def snapshot(self) -> dict:
        out: dict = {"kind": self.kind, "help": self.help}
        series = []
        for key in sorted(self._children):
            child = self._children[key]
            entry: dict = {"labels": dict(zip(self.labelnames, key))}
            if self.kind in ("counter", "gauge"):
                entry["value"] = child.value
            else:
                entry.update(
                    sum=child.sum, count=child.count,
                    buckets=list(child.buckets),
                    counts=list(child.counts),
                )
                for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                    v = child.quantile(q)
                    if v is not None:
                        entry[name] = v
            series.append(entry)
        out["series"] = series
        return out


class MetricsRegistry:
    """Get-or-create families by name; render the whole set.

    Re-declaring an existing name is the COMMON path (every engine in
    the process declares the same serving families) and must return the
    same family; a kind/label/bucket mismatch is a programming error
    and raises."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get(self, name, kind, help, labelnames, buckets=None) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r}")
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = _Family(name, kind, help, labelnames, buckets)
                    self._families[name] = fam
                    return fam
        if (
            fam.kind != kind
            or fam.labelnames != labelnames
            or (buckets is not None and fam.buckets != buckets)
        ):
            raise ValueError(
                f"metric {name!r} re-declared with a different "
                f"kind/labels/buckets (have {fam.kind}/{fam.labelnames})"
            )
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._get(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._get(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket edge")
        return self._get(name, "histogram", help, labelnames, buckets)

    def render(self) -> str:
        """The full Prometheus text exposition (``GET /metrics``)."""
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        return "\n".join(f.render() for f in fams) + "\n"

    def snapshot(self) -> dict:
        """JSON-able view of every family (``GET /statz``)."""
        with self._lock:
            fams = dict(self._families)
        return {name: fams[name].snapshot() for name in sorted(fams)}

    def quantile(self, name: str, q: float,
                 labels: Optional[dict] = None) -> Optional[float]:
        """Estimated quantile over a histogram family, pooling every
        child whose labels are a superset of ``labels`` (None = all
        children — e.g. ttft across every replica)."""
        fam = self._families.get(name)
        if fam is None or fam.kind != "histogram":
            return None
        want = {k: str(v) for k, v in (labels or {}).items()}
        counts = [0] * (len(fam.buckets) + 1)
        total = 0
        for key, child in list(fam._children.items()):
            kv = dict(zip(fam.labelnames, key))
            if any(kv.get(k) != v for k, v in want.items()):
                continue
            for i, c in enumerate(child.counts):
                counts[i] += c
            total += child.count
        return _bucket_quantile(fam.buckets, counts, total, q)

    def value(self, name: str, labels: Optional[dict] = None) -> float:
        """Summed counter/gauge value over matching children (0 when the
        family or combination does not exist — convenient for tests)."""
        fam = self._families.get(name)
        if fam is None or fam.kind == "histogram":
            return 0.0
        want = {k: str(v) for k, v in (labels or {}).items()}
        total = 0.0
        for key, child in list(fam._children.items()):
            kv = dict(zip(fam.labelnames, key))
            if any(kv.get(k) != v for k, v in want.items()):
                continue
            total += child.value
        return total


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+-?\d+)?$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_exposition(text: str) -> Dict[tuple, float]:
    """Parse Prometheus text exposition into
    ``{(name, frozenset(label_items)): value}`` — the assertion surface
    for tests and the driver's dryrun scrape. Raises ValueError on a
    line that matches neither a comment nor the sample grammar, so the
    parse doubles as a conformance check."""
    out: Dict[tuple, float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {raw!r}")
        name, labelblob, value = m.groups()
        labels = {}
        if labelblob:
            consumed = 0
            for lm in _LABEL_PAIR_RE.finditer(labelblob):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed = lm.end()
            rest = labelblob[consumed:].strip(", ")
            if rest:
                raise ValueError(
                    f"unparseable label block in line: {raw!r}"
                )
        v = math.inf if value == "+Inf" else float(value)
        out[(name, frozenset(labels.items()))] = v
    return out
