"""SLO watchdog: declared budgets evaluated over sliding windows.

A server that is *degraded* — p99 TTFT past budget, step times
ballooning (recompile storm, HBM paging), a queue that never drains, a
training run stuck skipping NaN gradients — looks identical to a
healthy-but-busy one from outside. The watchdog turns declared budgets
into a ``status`` ("ok" | "degraded" | "dead") with concrete reason
strings, surfaced on ``/healthz`` and ``/debugz``.

Every budget is evaluated over a SLIDING window, not run-to-date
aggregates (a bad first minute must not condemn a recovered server):

  * ``p99_ttft_ms`` / ``p99_itl_ms`` — from the engine's rolling
    last-256-completions trace window (``Engine.latency_stats()``:
    ``ttft_ms_p99`` and ``req_itl_ms_p99``, the per-request mean
    inter-token gap's window p99).
  * ``max_step_ms`` — p99 of the last ``window_steps`` engine-step
    durations recorded in the flight ring (obs/flight.py), which is
    itself a sliding window.
  * ``max_queue_depth`` — the CURRENT engine queue + runner inbox.

The evaluate() consumer is pull-based (the /healthz handler), so the
watchdog costs nothing on the engine hot path. It covers every engine
class through the uniform ``counters()``/``latency_stats()`` protocol —
``Engine``, ``PagedEngine``, both speculative engines, and
``ReplicatedEngine`` (whose pooled windows span all replicas) — and the
train loop's sick-run detector via :meth:`note_sick` /
:meth:`clear_sick` (train/loop.py calls them at the log cadence).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Budgets; ``None`` disables that check (the default watchdog with
    no budgets only ever reports "ok"/"dead")."""

    p99_ttft_ms: Optional[float] = None
    p99_itl_ms: Optional[float] = None
    max_step_ms: Optional[float] = None
    max_queue_depth: Optional[int] = None
    # Sliding-window sizing / flap guards: a budget only trips once its
    # window holds enough samples to mean something.
    window_steps: int = 128
    min_completions: int = 4
    min_steps: int = 8

    def active(self) -> bool:
        return any(
            v is not None
            for v in (self.p99_ttft_ms, self.p99_itl_ms,
                      self.max_step_ms, self.max_queue_depth)
        )


def _window_p99(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    return vals[min(int(0.99 * len(vals)), len(vals) - 1)]


class SLOWatchdog:
    """Evaluate ``cfg`` against a live engine; see module docstring.

    ``registry``/``flight`` default to the process-global sinks. The
    result of the last :meth:`evaluate` stays on :attr:`last` (the
    /debugz payload reads it without re-evaluating mid-render), a
    ``shifu_slo_degraded`` gauge mirrors it for scrapes, and each
    breach bumps ``shifu_slo_breaches_total{budget=...}``.
    """

    def __init__(self, cfg: Optional[SLOConfig] = None, *,
                 registry=None, flight=None):
        from shifu_tpu import obs

        self.cfg = cfg if cfg is not None else SLOConfig()
        self.registry = registry if registry is not None else obs.REGISTRY
        self.flight = flight if flight is not None else obs.FLIGHT
        self._g_degraded = self.registry.gauge(
            "shifu_slo_degraded",
            "1 while any SLO budget is breached (or a sick run is "
            "flagged), else 0",
        ).labels()
        self._c_breach = self.registry.counter(
            "shifu_slo_breaches_total",
            "SLO budget breaches observed at evaluation time",
            labelnames=("budget",),
        )
        self._sick: Optional[str] = None
        self.last = {"status": "ok", "reasons": []}

    # ------------------------------------------------ sick-run signal
    def note_sick(self, reason: str) -> None:
        """Force 'degraded' with ``reason`` until :meth:`clear_sick`
        (the train loop's NaN-skip detector pushes here — its signal is
        push-shaped, unlike the pull-evaluated serving budgets)."""
        self._sick = str(reason)

    def clear_sick(self) -> None:
        self._sick = None

    # ------------------------------------------------------- evaluate
    def evaluate(self, engine=None, *, inbox_depth: int = 0,
                 fatal=None) -> dict:
        """One evaluation pass -> ``{"status", "reasons"}``.

        ``engine`` is anything speaking the uniform protocol
        (``latency_stats()`` + ``counters()``); ``inbox_depth`` adds the
        runner's not-yet-drained submissions to the queue budget;
        ``fatal`` (an exception) short-circuits to "dead"."""
        if fatal is not None:
            res = {
                "status": "dead",
                "reasons": [f"engine thread died: {fatal!r}"],
            }
            self._g_degraded.set(1.0)
            self.last = res
            return res
        cfg = self.cfg
        reasons: List[str] = []
        if self._sick:
            reasons.append(self._sick)
            self._c_breach.labels(budget="sick_run").inc()
        if engine is not None and (
            cfg.p99_ttft_ms is not None or cfg.p99_itl_ms is not None
        ):
            lat = engine.latency_stats()
            if lat.get("completions", 0) >= cfg.min_completions:
                v = lat.get("ttft_ms_p99")
                if cfg.p99_ttft_ms is not None and v is not None \
                        and v > cfg.p99_ttft_ms:
                    reasons.append(
                        f"p99 TTFT {v:.1f} ms > budget "
                        f"{cfg.p99_ttft_ms:g} ms (window of "
                        f"{lat['completions']} completions)"
                    )
                    self._c_breach.labels(budget="p99_ttft_ms").inc()
                v = lat.get("req_itl_ms_p99")
                if cfg.p99_itl_ms is not None and v is not None \
                        and v > cfg.p99_itl_ms:
                    reasons.append(
                        f"p99 inter-token latency {v:.2f} ms > budget "
                        f"{cfg.p99_itl_ms:g} ms (window of "
                        f"{lat['completions']} completions)"
                    )
                    self._c_breach.labels(budget="p99_itl_ms").inc()
        if engine is not None and cfg.max_queue_depth is not None:
            q = int(engine.counters().get("queued", 0)) + int(inbox_depth)
            if q > cfg.max_queue_depth:
                reasons.append(
                    f"queue depth {q} > budget {cfg.max_queue_depth}"
                )
                self._c_breach.labels(budget="max_queue_depth").inc()
        if engine is not None and cfg.p99_ttft_ms is not None:
            # Fleet-pooled view: a router exposes federated_quantile
            # (the pooled shifu_fleet_agg_* histogram from its last
            # /metrics federation scrape). The router's OWN latency
            # window only sees requests routed through THIS router;
            # the pooled histogram sees each backend's whole history,
            # so the same TTFT budget also guards the aggregate.
            fed = getattr(engine, "federated_quantile", None)
            if callable(fed):
                try:
                    q = fed("shifu_request_ttft_seconds", 0.99)
                except Exception:  # noqa: BLE001 — scrape-shaped input
                    q = None
                if q is not None and q * 1000.0 > cfg.p99_ttft_ms:
                    reasons.append(
                        f"fleet pooled p99 TTFT {q * 1000.0:.1f} ms > "
                        f"budget {cfg.p99_ttft_ms:g} ms (federated "
                        "histogram)"
                    )
                    self._c_breach.labels(budget="fleet_ttft").inc()
        if cfg.max_step_ms is not None:
            durs = [
                e["dur_ms"]
                for e in self.flight.snapshot(
                    last=cfg.window_steps, kind="step"
                )
                if isinstance(e.get("dur_ms"), (int, float))
            ]
            if len(durs) >= cfg.min_steps:
                v = _window_p99(durs)
                if v is not None and v > cfg.max_step_ms:
                    reasons.append(
                        f"p99 engine step {v:.1f} ms > budget "
                        f"{cfg.max_step_ms:g} ms (last {len(durs)} steps)"
                    )
                    self._c_breach.labels(budget="max_step_ms").inc()
        res = {
            "status": "degraded" if reasons else "ok",
            "reasons": reasons,
        }
        self._g_degraded.set(1.0 if reasons else 0.0)
        self.last = res
        return res
