"""Per-request span records -> Chrome trace-event JSON.

The serving engines stamp each request's host wall-clock phases —
submit, admission, first token, finish — and the runner's ``trace_log``
persists one JSON line per completion (rid, finished_by, n_tokens plus
the ``Completion.timing`` spans, including ``t0_ms``, the submit stamp
on the engine's monotonic clock). This module turns those records into
the Chrome trace-event format (``chrome://tracing`` / Perfetto) — the
host-side complement to the device-side ``jax.profiler`` traces.

Span layout per request (all on the engine's monotonic clock):

  queue    [t0, t0 + queue_ms)                submit -> first admission
  prefill  [t0 + queue_ms, .. + prefill_ms)   admission dispatch(es)
  decode   [t0 + ttft_ms, .. + decode_ms)     first token -> finish

``prefill_ms`` also accumulates post-first-token re-prefills (chunked
prefill, preemption recompute), which could overlap the decode span;
the exporter clamps the prefill span at the decode start so tracks stay
well-formed, and carries the raw value in ``args`` for the curious.

Records carrying an explicit ``kind`` + ``dur_ms`` are generic single
spans (router hops, resubmits, backend hops recorded by the fleet
layer) and pass through as one event.

Lane assignment: one Chrome PROCESS lane per (host, replica) — two
replicas (or two hosts, in a merged fleet trace) with the same rid
must not interleave into one track — and one thread track per request
within its lane, named by Chrome metadata events so the viewer shows
``host · replica N`` / ``req R`` instead of bare integers.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

PHASES = ("queue", "prefill", "decode")

# Extra keys carried verbatim into each event's args block.
_ARG_KEYS = (
    "rid", "finished_by", "n_tokens", "preemptions", "prefill_ms",
    "decode_tokens_per_s", "trace_id", "span_id", "parent_id",
    "backend", "tier", "model",
)


def spans_from_record(rec: dict) -> List[dict]:
    """One trace-log record -> its Chrome trace events (without lane
    assignment — ``chrome_trace`` keys pids/tids by (host, replica)).
    May be empty for a record without timing spans."""
    args = {k: rec[k] for k in _ARG_KEYS if k in rec}
    if "kind" in rec:
        # Generic single-span record (router hop, resubmit, ...).
        return [{
            "name": str(rec["kind"]),
            "cat": "request",
            "ph": "X",
            "ts": round(float(rec.get("t0_ms", 0.0)) * 1000.0, 1),
            "dur": round(max(float(rec.get("dur_ms", 0.0)), 0.0)
                         * 1000.0, 1),
            "args": args,
        }]
    t0 = float(rec.get("t0_ms", 0.0))
    queue = max(float(rec.get("queue_ms", 0.0)), 0.0)
    prefill = max(float(rec.get("prefill_ms", 0.0)), 0.0)
    ttft = max(float(rec.get("ttft_ms", 0.0)), queue)
    decode = max(float(rec.get("decode_ms", 0.0)), 0.0)

    # Non-overlap invariants: queue ends where prefill starts; prefill
    # is clamped into [queue end, decode start]; decode starts at ttft
    # (>= queue + clamped prefill by construction).
    pre_end = min(queue + prefill, ttft)
    spans = (
        ("queue", t0, queue),
        ("prefill", t0 + queue, max(pre_end - queue, 0.0)),
        ("decode", t0 + ttft, decode),
    )
    events = []
    for name, start_ms, dur_ms in spans:
        events.append({
            "name": name,
            "cat": "request",
            "ph": "X",  # complete event: ts + dur
            "ts": round(start_ms * 1000.0, 1),   # microseconds
            "dur": round(dur_ms * 1000.0, 1),
            "args": args,
        })
    return events


def _lane_key(rec: dict) -> Tuple[str, str]:
    host = str(rec.get("host") or "local")
    return host, str(rec.get("replica", "0"))


def chrome_trace(records: Iterable[dict]) -> dict:
    """Trace-log records -> a Chrome trace-event JSON object with one
    process lane per (host, replica) and one named thread track per
    request within its lane."""
    events: List[dict] = []
    meta: List[dict] = []
    pids: Dict[Tuple[str, str], int] = {}
    tids: Dict[Tuple[int, object], int] = {}
    for rec in records:
        evs = spans_from_record(rec)
        if not evs:
            continue
        lane = _lane_key(rec)
        pid = pids.get(lane)
        if pid is None:
            pid = pids[lane] = len(pids) + 1
            host, replica = lane
            meta.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{host} · replica {replica}"},
            })
        track = rec.get("rid", rec.get("span_id", 0))
        tkey = (pid, track)
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = sum(
                1 for (p, _t) in tids if p == pid
            ) + 1
            meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"req {track}"},
            })
        for e in evs:
            e["pid"] = pid
            e["tid"] = tid
        events.extend(evs)
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "shifu_tpu trace export"},
    }


def export_trace_log(in_path: str, out_path: Optional[str] = None) -> dict:
    """Read a runner ``trace_log`` JSONL file and emit Chrome trace
    JSON — the ``shifu_tpu trace export`` implementation. Returns the
    trace object; when ``out_path`` is given the JSON is also written
    there. Unparseable lines are skipped (a crash mid-write leaves a
    torn last line; the rest of the log is still good)."""
    records = []
    with open(in_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    trace = chrome_trace(records)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
    return trace
