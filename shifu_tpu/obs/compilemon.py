"""Compile + device-memory telemetry.

A recompile storm is the classic silent TPU serving failure: a shape
the bucketing missed sends every Nth request through a multi-second
XLA compile, and from outside the server just looks slow. This module
makes compiles first-class metrics:

``tracked(fn, name)``
    Wrap a ``jax.jit``-ed callable. Each call compares the function's
    compile-cache size before/after; growth means THIS call compiled,
    so the call's wall time (compile + first execution — the stall a
    client actually experiences) lands in
    ``shifu_compile_seconds{fn=...}`` and bumps
    ``shifu_compile_total{fn=...}``. A ``compile`` event also goes to
    the flight ring, so /debugz shows compiles interleaved with the
    step timeline. The per-call overhead is one ``_cache_size()``
    C++ call (~1 µs) — the serving engines wrap their prefill/decode/
    round programs with this (infer/engine.py, infer/spec_engine.py).

``install_jax_monitoring()``
    Register a ``jax.monitoring`` duration listener mirroring every
    backend event whose name mentions "compile" into
    ``shifu_jax_compile_seconds{event=...}`` — the global, no-wrapper
    view (tracing + lowering + backend compile), complementing the
    per-function wrappers. Idempotent; a JAX build without the hook
    degrades to a no-op.

``update_memory_gauges()``
    Sample ``utils.profiling.device_memory_stats()`` into
    ``shifu_hbm_bytes_in_use / shifu_hbm_peak_bytes_in_use /
    shifu_hbm_bytes_limit{device=...}`` gauges. Sample-on-scrape: the
    /metrics and /statz handlers call it per request (memory_stats can
    RPC on tunnelled backends — too hot for the step loop). Backends
    that return no stats (CPU) simply contribute no series.
"""

from __future__ import annotations

import time
from typing import Optional

# Compile times are seconds-scale (bucketed separately from the
# latency-shaped default buckets).
COMPILE_BUCKETS = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)


class _TrackedJit:
    """Callable proxy over one jitted function; see module docstring.
    Proxies only ``__call__`` — the engines never touch other
    attributes of their compiled programs on the hot path."""

    __slots__ = ("_fn", "name", "_c", "_h", "_flight", "_sizable")

    def __init__(self, fn, name: str, registry, flight):
        self._fn = fn
        self.name = name
        self._c = registry.counter(
            "shifu_compile_total",
            "Compiles observed per tracked jitted function (cache-size "
            "growth on a call)",
            labelnames=("fn",),
        ).labels(fn=name)
        self._h = registry.histogram(
            "shifu_compile_seconds",
            "Wall time of calls that compiled (compile + first "
            "execution — the stall a caller experiences)",
            labelnames=("fn",),
            buckets=COMPILE_BUCKETS,
        ).labels(fn=name)
        self._flight = flight
        # Not every callable exposes _cache_size (plain functions in
        # tests, future jax versions): degrade to pass-through.
        self._sizable = hasattr(fn, "_cache_size")

    def _size(self) -> Optional[int]:
        if not self._sizable:
            return None
        try:
            return self._fn._cache_size()
        except Exception:
            self._sizable = False
            return None

    def __call__(self, *args, **kwargs):
        before = self._size()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        if before is not None:
            after = self._size()
            if after is not None and after > before:
                dt = time.perf_counter() - t0
                self._c.inc()
                self._h.observe(dt)
                if self._flight is not None:
                    self._flight.record(
                        "compile", fn=self.name,
                        dur_ms=round(dt * 1000.0, 2),
                        cache_size=after,
                    )
        return out


def tracked(fn, name: str, registry=None, flight=None) -> _TrackedJit:
    """Wrap a jitted callable with compile tracking (see _TrackedJit)."""
    from shifu_tpu import obs

    return _TrackedJit(
        fn, name,
        registry if registry is not None else obs.REGISTRY,
        flight if flight is not None else obs.FLIGHT,
    )


_monitoring_installed = False


def install_jax_monitoring(registry=None) -> bool:
    """Mirror jax.monitoring compile-duration events into the registry
    (idempotent; returns whether the listener is installed)."""
    global _monitoring_installed
    if _monitoring_installed:
        return True
    from shifu_tpu import obs

    reg = registry if registry is not None else obs.REGISTRY
    try:
        import jax.monitoring as _mon

        register = _mon.register_event_duration_secs_listener
    except (ImportError, AttributeError):
        return False
    fam = reg.histogram(
        "shifu_jax_compile_seconds",
        "jax.monitoring duration events mentioning 'compile' "
        "(tracing/lowering/backend compile)",
        labelnames=("event",),
        buckets=COMPILE_BUCKETS,
    )

    def _listener(event, duration, **kw):
        # Listener runs inside jax dispatch — never raise out of it.
        try:
            if "compile" in event:
                fam.labels(event=event).observe(float(duration))
        except Exception:
            pass

    register(_listener)
    _monitoring_installed = True
    return True


_HBM_GAUGES = (
    ("bytes_in_use", "shifu_hbm_bytes_in_use",
     "Device memory currently allocated (bytes)"),
    ("peak_bytes_in_use", "shifu_hbm_peak_bytes_in_use",
     "High-water device memory (bytes)"),
    ("bytes_limit", "shifu_hbm_bytes_limit",
     "Device memory capacity visible to the allocator (bytes)"),
)


def update_memory_gauges(registry=None) -> int:
    """Sample per-device memory stats into gauges; returns how many
    series were updated (0 on backends that expose no stats — the CPU
    path, tested in tests/test_selfdiag.py)."""
    from shifu_tpu import obs
    from shifu_tpu.utils.profiling import device_memory_stats

    reg = registry if registry is not None else obs.REGISTRY
    updated = 0
    try:
        stats = device_memory_stats()
    except Exception:
        return 0
    for d in stats:
        dev = d.get("device", "?")
        for key, gname, ghelp in _HBM_GAUGES:
            v = d.get(key)
            if v is None:
                continue
            reg.gauge(gname, ghelp, labelnames=("device",)).labels(
                device=dev
            ).set(float(v))
            updated += 1
    return updated
