"""Bench regression gate: compare a compact bench line against a
recorded baseline within declared tolerances.

Five rounds of BENCH_rNN.json exist and none was ever CHECKED — a perf
regression only surfaced if a human compared JSON by eye. The gate
turns the trajectory into an enforced contract:

    python bench.py --baseline BENCH_r05.json        # gate after the run
    python -m shifu_tpu obs check-bench \
        --baseline BENCH_r05.json --current line.json  # offline compare

Each headline metric declares a DIRECTION (is higher or lower better?)
and a RELATIVE tolerance sized to its measured round-to-round noise
(tunnel-fitted device times wobble a few percent; acceptance rates and
speedup ratios more). A metric regresses when it moves PAST tolerance
in the bad direction; improvements of any size pass. Metrics missing
from either side are skipped (legs grow and shrink across rounds) —
the gate checks what both rounds measured, and reports what it
skipped so silent coverage loss is visible.

Key renames are aliased (``spec_round_cost_only_ms`` reads old
baselines' ``spec_round_dev_ms``), so the gate works against the
pre-rename BENCH_r05.json unchanged.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

HIGHER = "higher"  # bigger is better (throughput, MFU, speedup ratios)
LOWER = "lower"    # smaller is better (latencies, step/round times)

# metric key -> (direction, relative tolerance). Tolerances encode each
# metric's observed round-to-round noise (see module docstring).
METRIC_SPECS: Dict[str, Tuple[str, float]] = {
    # train headline
    "value": (HIGHER, 0.10),            # train tokens/s
    "mfu": (HIGHER, 0.08),
    "step_ms": (LOWER, 0.10),
    # serving decode, chip-true (two-point tunnel fit: a few % noise)
    "sv_bf16_dev_ms": (LOWER, 0.15),
    "sv_int8_dev_ms": (LOWER, 0.15),
    "sv_kv8_dev_ms": (LOWER, 0.15),
    "sv_kv8b_dev_ms": (LOWER, 0.15),
    "sv_bf16_bw": (HIGHER, 0.15),
    "sv_int8_bw": (HIGHER, 0.15),
    "sv_kv8_bw": (HIGHER, 0.15),
    "sv_kv8b_bw": (HIGHER, 0.15),
    "sv_bf16_tps": (HIGHER, 0.15),
    "sv_prefill_ms": (LOWER, 0.25),
    # serving latency distributions (registry histograms; wall-clock
    # through the tunnel — widest tolerance)
    "p50_ttft_ms": (LOWER, 0.35),
    "p99_itl_ms": (LOWER, 0.35),
    # induction / lookup / constrained speculation
    "ind_x_plain": (HIGHER, 0.15),
    "ind_tps_dev": (HIGHER, 0.15),
    "ind_plain_tps_dev": (HIGHER, 0.15),
    "cst_x_plain": (HIGHER, 0.20),
    "cst_tps_dev": (HIGHER, 0.20),
    "txt_x_plain": (HIGHER, 0.20),
    "txt_tps_dev": (HIGHER, 0.20),
    "txt_acc": (HIGHER, 0.20),
    "txt_tpr": (HIGHER, 0.20),
    "lkp_round_dev_ms": (LOWER, 0.20),
    "dft_x_plain": (HIGHER, 0.20),
    "dft_acc": (HIGHER, 0.20),
    "dft_round_dev_ms": (LOWER, 0.20),
    # draft-spec round-cost decomposition (renamed keys; aliased below)
    "spec_round_cost_only_ms": (LOWER, 0.20),
    # secondary train legs
    "lc_mfu": (HIGHER, 0.08),
    "lcw_mfu": (HIGHER, 0.08),
    "lcw_ms": (LOWER, 0.10),
    "lcw2_mfu": (HIGHER, 0.08),
    "lcw2_ms": (LOWER, 0.10),
    # Gemma-2-shaped leg (ISSUE 4): softcap + alternating windows on
    # the flash path, plus the flash-vs-XLA-oracle ratio — the ratio
    # collapsing toward 1 means the family silently fell back to the
    # O(S^2) XLA path.
    "g2_mfu": (HIGHER, 0.08),
    "g2_ms": (LOWER, 0.10),
    "g2_x_xla": (HIGHER, 0.10),
    "moe_mfu": (HIGHER, 0.10),
    # grouped-vs-dense MoE dispatch ratio (round 6): collapsing to ~1
    # means the grouped default silently regressed to einsum cost.
    "moe_x_dense": (HIGHER, 0.10),
    # fleet-routed overhead (round 7): routed-vs-direct wall ratio and
    # routed request time through the FleetRouter hop. Armable —
    # dormant until a baseline round records the leg (missing keys are
    # skipped); once recorded, the ratio drifting UP past tolerance
    # means the router grew a per-request/per-token hot-path cost.
    "fleet_x_direct": (LOWER, 0.35),
    "fleet_rt_ms": (LOWER, 0.35),
    # zero-downtime rollout leg (round 8): client-visible p99 TTFT
    # during a synthetic rolling weight update, and the error rate
    # clients saw while it ran. Armable — dormant until a baseline
    # round records the leg; rollout_err_rate additionally stays
    # dormant while the recorded baseline is 0 (ratio gates need a
    # nonzero anchor — check_bench skips zero baselines), so the p99
    # row is the live guard against the rollout machinery growing a
    # client-visible cost.
    "rollout_p99_ttft_ms": (LOWER, 0.35),
    "rollout_err_rate": (LOWER, 0.50),
    # offline batch tier (round 9): sustained job throughput over the
    # 10^4-request soak and the interactive p99-TTFT tax of backfill.
    # Armable — dormant until a baseline round records the leg; the
    # tax row additionally stays dormant while the recorded baseline
    # is 0 (check_bench skips zero baselines), so batch_tok_s is the
    # live guard against the batch path losing throughput, and the
    # tax row arms the first time a round measures a nonzero tax.
    "batch_tok_s": (HIGHER, 0.20),
    "batch_ttft_tax_ms": (LOWER, 0.50),
    # kernel autotuner (round 10): tuned-vs-default (v0) step-time
    # ratios per soft-spot leg, measured by bench.py --tune-table
    # re-running each leg with the winner table active vs disabled.
    # >= 1 means the table's winners actually pay off on this device;
    # collapsing below 1 - tol means a stale table is now HURTING and
    # needs a re-tune. Armable — dormant until a TPU baseline round
    # records them (bench without --tune-table emits no ratio).
    "lcw_tune_x_default": (HIGHER, 0.10),
    "g2_tune_x_default": (HIGHER, 0.10),
    "moe_tune_x_default": (HIGHER, 0.10),
    # tiered KV cache (round 11): measured restore-vs-recompute ratio
    # (restored tokens per ms of transfer over prefilled tokens per ms
    # of compute — >= 1 means restoring spilled pages beats paying the
    # prefill again on this chip) and the cache-served share of prompt
    # tokens under bench_kv_tier's eviction-pressure multi-turn trace.
    # Armable — dormant until a TPU baseline round records the leg
    # (missing keys are skipped with a machine-readable reason, like
    # the *_tune_x_default rows).
    "kv_restore_x_recompute": (HIGHER, 0.20),
    "kv_hit_rate": (HIGHER, 0.15),
    # prefill/decode disaggregation (round 14): p99 TTFT/ITL of the
    # two-host handoff path over the same decode host colocated
    # (bench_disagg). Armable — dormant until a baseline round records
    # the leg (missing keys are skipped). The TTFT ratio prices the
    # migration (prefill hop + SKVP transfer) and drifting UP past
    # tolerance means the handoff got more expensive; the ITL ratio
    # should sit ~1 — decode runs on one host either way — so it
    # creeping up means handoff cost leaked into steady-state decode.
    "disagg_x_coloc_ttft": (LOWER, 0.50),
    "disagg_x_coloc_itl": (LOWER, 0.35),
    # sticky routing + live migration (round 18): bench_sticky_routing
    # replays one deterministic multi-turn chat trace through a sticky
    # fleet and a cache-oblivious round-robin control. The saved-x
    # ratio is oblivious computed-prefill tokens over sticky (>1 =
    # session affinity turned follow-up turns into cache hits) — it collapsing toward 1 means affinity stopped
    # placing sessions on their pages. migrate_x_cold_ttft prices a
    # drain-forced mid-session migration against a cold same-length
    # prefill on the surviving host; drifting UP past tolerance means
    # the export/ingest walk got more expensive than the prefill it
    # avoids. Armable — dormant until a baseline round records the leg
    # (missing keys are skipped).
    "sticky_prefill_tok_saved_x": (HIGHER, 0.25),
    "sticky_p50_ttft_ms": (LOWER, 0.50),
    "migrate_x_cold_ttft": (LOWER, 0.50),
    # fleet prefix store (round 19): bench_kv_fleet warms a stone-cold
    # host from its peer over GET /kv/pages?digest= and prices a new
    # session's first turn against a cold control engine. The ratio is
    # peer-warmed computed-prefill tokens over cold (< 1 = the fetched
    # chains turned the shared system prompt into cache hits); it
    # drifting UP past tolerance means peer warming stopped covering
    # the shared prefix. kvf_warmup_ms prices the bulk pull itself.
    # Armable — dormant until a baseline round records the leg
    # (missing keys are skipped).
    "kvf_peer_x_cold": (LOWER, 0.35),
    "kvf_warmup_ms": (LOWER, 0.50),
    # loadgen measurement harness (round 17): the headline of a scored
    # scenario run (shifu_tpu loadgen / bench_loadgen) — goodput and
    # achieved-vs-offered are the capacity claims, p99 TTFT and error
    # rate the SLO ones. Armable — dormant until a baseline round
    # records a run (missing keys skip with a machine-readable
    # reason); lg_err_rate additionally stays dormant while the
    # recorded baseline is 0 (check_bench skips zero baselines), so
    # goodput + achieved_x_offered are the live guards against the
    # serving path losing capacity under the standing mix.
    "lg_goodput_rps": (HIGHER, 0.25),
    "lg_achieved_x_offered": (HIGHER, 0.15),
    "lg_p99_ttft_ms": (LOWER, 0.50),
    "lg_err_rate": (LOWER, 0.50),
    # elastic fleet control plane (round 20): bench_autoscale drives a
    # loadgen ramp with a shifting prefill/decode mix against an
    # elastic fleet (standby pool + autoscale controller) and a
    # fixed-size fixed-role control. as_p99_ttft_ms is the elastic
    # fleet's client-visible tail under the ramp; as_scale_actions
    # counts completed pool/role actions (it collapsing to 0 means the
    # controller stopped reacting to the same stimulus);
    # as_flip_lag_s prices one drain-flip-resume role change
    # end-to-end; as_backfill_util is the batch-tier admission
    # fraction the envelope sustained (1 = never throttled more than
    # declared). Armable — dormant until a baseline round records the
    # leg (missing keys are skipped with a machine-readable reason).
    "as_p99_ttft_ms": (LOWER, 0.50),
    "as_scale_actions": (HIGHER, 0.75),
    "as_flip_lag_s": (LOWER, 0.75),
    "as_backfill_util": (HIGHER, 0.50),
}

# Absolute floors for landed improve-direction wins (round 6): relative
# tolerance alone lets a landed optimisation erode a few percent per
# round, forever. Once a recorded BASELINE meets the floor, every later
# round must stay at or above it. DORMANT while the baseline itself is
# below the floor, so pre-win baselines (BENCH_r05 and earlier) gate
# unchanged — the floor arms the first time a round records the win
# (BENCH_r06 onward).
METRIC_FLOORS: Dict[str, float] = {
    "moe_mfu": 0.45,   # grouped MoE dispatch (from 0.2877 einsum)
    "lcw_mfu": 0.58,   # windowed forced-grid KV-block lever (from 0.5104)
    # Gemma-2 softcap+alternating-window flash path (ISSUE 4): arms
    # the first time a round records the win (windowed-config MFU sat
    # at 0.51 on the refused-to-XLA route; half the stack is full
    # attention at s=4096, so the dense-leg ~0.63 is the ceiling).
    "g2_mfu": 0.55,
    # Tiered KV cache (ISSUE 11): the tier only earns its keep while
    # restore actually beats recompute — arms the first time a TPU
    # baseline records the ratio at or above 1.0, then never lets it
    # sink below breakeven unnoticed.
    "kv_restore_x_recompute": 1.0,
}

# current-key -> acceptable baseline keys (oldest last): lets a renamed
# compact line gate against pre-rename baselines.
BASELINE_ALIASES: Dict[str, Tuple[str, ...]] = {
    "spec_round_cost_only_ms": ("spec_round_dev_ms",),
    "spec_round_cost_only_acc": ("spec_acc",),
}


def load_record(path: str) -> dict:
    """A compact bench line from ``path``: accepts the driver's
    BENCH_rNN.json shape ({"parsed": {...}}), a raw compact line, or a
    full ledger (which carries the same top-level headline keys)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _baseline_value(baseline: dict, key: str):
    if key in baseline:
        return baseline[key]
    for alias in BASELINE_ALIASES.get(key, ()):
        if alias in baseline:
            return baseline[alias]
    return None


def check_bench(current: dict, baseline: dict,
                specs: Optional[Dict[str, Tuple[str, float]]] = None,
                scale_tol: float = 1.0) -> Tuple[bool, dict]:
    """Gate ``current`` against ``baseline``; returns (ok, report).

    ``scale_tol`` multiplies every declared tolerance (a hurried
    operator can loosen the whole gate without editing specs). The
    report lists every checked metric with its ratio and verdict,
    plus the keys skipped on each side.
    """
    specs = specs if specs is not None else METRIC_SPECS
    rows = []
    regressions = []
    skipped = []
    for key, (direction, tol) in specs.items():
        cur = current.get(key)
        base = _baseline_value(baseline, key)
        # Machine-readable skip reasons ("reason" codes; "why" stays
        # the human prose): the TPU driver reads these to see which
        # gate rows — and which METRIC_FLOORS — are still dormant.
        if not isinstance(cur, (int, float)) or isinstance(cur, bool):
            if isinstance(base, (int, float)):
                skipped.append({
                    "key": key, "why": "missing in current",
                    "reason": "missing_current",
                })
            continue
        if not isinstance(base, (int, float)) or isinstance(base, bool):
            skipped.append({
                "key": key, "why": "missing in baseline",
                "reason": "missing_baseline",
            })
            continue
        if base == 0:
            skipped.append({
                "key": key, "why": "baseline is 0",
                "reason": "zero_baseline",
            })
            continue
        ratio = cur / base
        tol = tol * scale_tol
        if direction == HIGHER:
            bad = ratio < 1.0 - tol
        else:
            bad = ratio > 1.0 + tol
        # Armed absolute floor: the baseline reached this win, so the
        # current round may not fall below it even inside relative
        # tolerance (see METRIC_FLOORS).
        floor = METRIC_FLOORS.get(key)
        floored = (
            floor is not None and direction == HIGHER
            and base >= floor and cur < floor
        )
        row = {
            "key": key,
            "baseline": base,
            "current": cur,
            "ratio": round(ratio, 4),
            "direction": direction,
            "tolerance": round(tol, 4),
            "verdict": (
                "BELOW_FLOOR" if (floored and not bad)
                else ("REGRESSED" if bad else "ok")
            ),
        }
        if floor is not None and base >= floor:
            row["floor"] = floor
        bad = bad or floored
        rows.append(row)
        if bad:
            regressions.append(row)
    ok = not regressions
    # Floor ledger: every declared METRIC_FLOORS row with its armed/
    # dormant state and a machine-readable reason — the driver's view
    # of which wins have landed and which are still awaited.
    checked_by_key = {r["key"]: r for r in rows}
    floors = []
    for key, floor in METRIC_FLOORS.items():
        row = checked_by_key.get(key)
        base = _baseline_value(baseline, key)
        if row is not None:
            armed = row["baseline"] >= floor
            state = {
                "key": key, "floor": floor,
                "baseline": row["baseline"], "current": row["current"],
                "state": "armed" if armed else "dormant",
            }
            if not armed:
                state["reason"] = "baseline_below_floor"
        else:
            state = {
                "key": key, "floor": floor, "state": "dormant",
                "reason": (
                    "zero_baseline" if base == 0 else "not_measured"
                ),
            }
        floors.append(state)
    report = {
        "status": "pass" if ok else "fail",
        "checked": len(rows),
        "regressions": regressions,
        "skipped": skipped,
        "floors": floors,
        "dormant_floors": [
            f["key"] for f in floors if f["state"] == "dormant"
        ],
        "rows": rows,
    }
    return ok, report


def check_tune(old_path: str, new_path: str) -> Tuple[bool, dict]:
    """Diff two kernel tune-table artifacts (``shifu_tpu obs
    check-tune``): the winner table is a reviewable, gated fact like a
    BENCH row, so a winner changing between tunes must surface as a
    non-zero exit a human signs off on, never a silent behavioral
    drift. Returns (identical, report); raises OSError /
    tune.table.TuneTableError on unusable artifacts (CLI exit 2)."""
    from shifu_tpu.tune.table import diff_tables, load_table

    old = load_table(old_path)
    new = load_table(new_path)
    report = diff_tables(old, new)
    report["baseline"] = old_path
    report["current"] = new_path
    return report["status"] == "identical", report
