"""Cross-host incident bundles: automatic forensics on an SLO breach.

When a tier's error budget starts burning (obs/slo.py), the state that
explains WHY is spread across the fleet and about to be overwritten:
each backend's flight-recorder ring wraps, span stores evict, and the
federated counters keep moving. An incident bundle freezes all of it
into one timestamped on-disk directory the moment the breach is
detected:

  * ``flight_router.json`` — the router's own flight ring tail;
  * ``flight_<backend>.json`` — every reachable backend's ``GET
    /debugz?n=K`` document (the tail limit bounds the fleet-wide
    scrape's payload — a 64-host fleet must not ship 64 full rings);
  * ``trace_<id>.json`` — the most recent distributed traces, each
    merged across hosts exactly like ``shifu_tpu trace export`` does
    (clock offsets applied);
  * ``metrics_federated.prom`` / ``metrics_router.prom`` — the pooled
    ``shifu_fleet_agg_*`` exposition and the router's own registry;
  * ``slo.json`` — the breaching tier's /sloz block;
  * ``manifest.json`` — what was captured, from whom, what failed.

Captures are RATE-LIMITED (``min_interval_s``): a flapping budget must
produce one bundle per quiet period, not one per evaluation tick — the
check-and-reserve is atomic so concurrent breach paths (the monitor
thread racing a /sloz scrape) still write exactly one. Per-backend
fetch failures are recorded in the manifest instead of failing the
bundle — a dead host is usually the STORY, and its absence is itself
evidence.

Inspect with ``shifu_tpu obs incident list | show | export`` (cli.py).
"""

from __future__ import annotations

import json
import os
import re
import tarfile
import threading
import time
from typing import Callable, List, Optional

MANIFEST = "manifest.json"
_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def _safe_name(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", str(s))


class IncidentWriter:
    """Rate-limited bundle capture into ``root``.

    ``debug_tail`` bounds each backend ``/debugz`` fetch (the ``?n=``
    tail limit); ``max_traces`` bounds how many recent distributed
    traces are merged into the bundle. ``clock`` (monotonic-like) is
    injectable for the rate-limit tests; directory names use the wall
    clock."""

    def __init__(self, root: str, *, min_interval_s: float = 900.0,
                 debug_tail: int = 256, max_traces: int = 3,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, flight=None):
        from shifu_tpu import obs as _obs

        self.root = str(root)
        self.min_interval_s = float(min_interval_s)
        self.debug_tail = int(debug_tail)
        self.max_traces = int(max_traces)
        self.clock = clock
        self.flight = flight if flight is not None else _obs.FLIGHT
        reg = metrics if metrics is not None else _obs.REGISTRY
        self._c_incidents = reg.counter(
            "shifu_slo_incidents_total",
            "Incident bundles captured (rate-limited breach "
            "forensics)", labelnames=("tier",),
        )
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self.captured = 0
        self.suppressed = 0

    # ----------------------------------------------------- capture
    def capture(self, source, *, tier: str, reason: str,
                slo: Optional[dict] = None) -> Optional[str]:
        """Capture one bundle from ``source`` (a FleetRouter-shaped
        object: ``flight`` / ``backends`` / ``trace_spans`` /
        ``recent_trace_ids`` / ``federated_metrics`` / ``metrics`` —
        every facet optional, missing ones are skipped). Returns the
        bundle directory path, or None when rate-limited."""
        with self._lock:
            now = self.clock()
            if self._last is not None and (
                now - self._last < self.min_interval_s
            ):
                self.suppressed += 1
                return None
            self._last = now

        wall = time.time()
        stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime(wall))
        base = f"incident_{stamp}_{_safe_name(tier)}"
        path = os.path.join(self.root, base)
        n = 2
        while os.path.exists(path):
            path = os.path.join(self.root, f"{base}_{n}")
            n += 1
        os.makedirs(path)
        incident_id = os.path.basename(path)

        files: List[dict] = []
        backends_report: dict = {}
        errors: List[str] = []

        def write_json(name: str, doc) -> None:
            p = os.path.join(path, name)
            with open(p, "w", encoding="utf-8") as f:
                json.dump(doc, f)
                f.write("\n")
            files.append({"name": name, "bytes": os.path.getsize(p)})

        def write_text(name: str, text: str) -> None:
            p = os.path.join(path, name)
            with open(p, "w", encoding="utf-8") as f:
                f.write(text)
            files.append({"name": name, "bytes": os.path.getsize(p)})

        # Router's own flight ring tail.
        fl = getattr(source, "flight", None)
        if fl is not None:
            try:
                write_json("flight_router.json", {
                    "capacity": fl.capacity, "dropped": fl.dropped,
                    "events": fl.snapshot(last=self.debug_tail),
                })
            except Exception as e:  # noqa: BLE001 — best-effort
                errors.append(f"flight_router: {e}")

        # Every backend's bounded /debugz ring.
        for b in getattr(source, "backends", None) or ():
            if getattr(b, "detached", False):
                continue
            try:
                doc = b.debugz(n=self.debug_tail)
            except Exception as e:  # noqa: BLE001 — dead host IS data
                backends_report[b.addr] = f"error: {e}"
                continue
            write_json(f"flight_{_safe_name(b.addr)}.json", doc)
            backends_report[b.addr] = "ok"

        # Most recent distributed traces, merged across hosts.
        trace_ids: List[str] = []
        recent = getattr(source, "recent_trace_ids", None)
        if callable(recent):
            try:
                trace_ids = list(recent(self.max_traces))
            except Exception as e:  # noqa: BLE001
                errors.append(f"recent_trace_ids: {e}")
        spans = getattr(source, "trace_spans", None)
        if callable(spans) and trace_ids:
            from shifu_tpu.obs.disttrace import merge_host_docs

            for tid in trace_ids:
                try:
                    merged = merge_host_docs(spans(tid), trace_id=tid)
                    write_json(f"trace_{_safe_name(tid)}.json", merged)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"trace {tid}: {e}")

        # Federated + local metric snapshots.
        fed = getattr(source, "federated_metrics", None)
        if callable(fed):
            try:
                write_text("metrics_federated.prom", fed() or "")
            except Exception as e:  # noqa: BLE001
                errors.append(f"federated_metrics: {e}")
        reg = getattr(source, "metrics", None)
        if reg is not None:
            try:
                write_text("metrics_router.prom", reg.render())
            except Exception as e:  # noqa: BLE001
                errors.append(f"metrics_router: {e}")

        if slo is not None:
            write_json("slo.json", slo)

        manifest = {
            "id": incident_id,
            "captured_at": wall,
            "tier": str(tier),
            "reason": str(reason),
            "backends": backends_report,
            "traces": trace_ids,
            "errors": errors,
            "files": files,
        }
        with open(os.path.join(path, MANIFEST), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=2)
            f.write("\n")

        self.captured += 1
        self._c_incidents.labels(tier=str(tier)).inc()
        self.flight.record(
            "incident_captured", tier=str(tier), reason=str(reason),
            path=path, backends=len(backends_report),
        )
        return path


# -------------------------------------------------------- inspection
def _check_id(incident_id: str) -> str:
    iid = str(incident_id)
    if not _ID_RE.match(iid):
        raise ValueError(f"bad incident id {iid!r}")
    return iid


def list_incidents(root: str) -> List[dict]:
    """Bundle summaries under ``root``, newest first (the ``obs
    incident list`` payload). Directories without a readable manifest
    are reported with an ``error`` field instead of being hidden."""
    out: List[dict] = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root), reverse=True):
        mpath = os.path.join(root, name, MANIFEST)
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath, encoding="utf-8") as f:
                m = json.load(f)
            out.append({
                "id": m.get("id", name),
                "captured_at": m.get("captured_at"),
                "tier": m.get("tier"),
                "reason": m.get("reason"),
                "files": len(m.get("files", ())),
                "backends": m.get("backends", {}),
            })
        except (OSError, ValueError) as e:
            out.append({"id": name, "error": str(e)})
    out.sort(key=lambda r: r.get("captured_at") or 0, reverse=True)
    return out


def load_manifest(root: str, incident_id: str) -> dict:
    iid = _check_id(incident_id)
    mpath = os.path.join(root, iid, MANIFEST)
    with open(mpath, encoding="utf-8") as f:
        return json.load(f)


def show_incident(root: str, incident_id: str) -> dict:
    """Manifest plus a per-file summary (event/sample counts) — the
    ``obs incident show`` payload."""
    m = load_manifest(root, incident_id)
    path = os.path.join(root, _check_id(incident_id))
    summaries = {}
    for ent in m.get("files", ()):
        name = ent.get("name", "")
        p = os.path.join(path, name)
        try:
            if name.endswith(".json"):
                with open(p, encoding="utf-8") as f:
                    doc = json.load(f)
                if "events" in doc:
                    summaries[name] = {"events": len(doc["events"])}
                elif "traceEvents" in doc:
                    summaries[name] = {
                        "trace_events": len(doc["traceEvents"]),
                        "hosts": doc.get("otherData", {}).get("hosts"),
                    }
                elif "tiers" in doc:
                    summaries[name] = {
                        t: d.get("status")
                        for t, d in doc["tiers"].items()
                    }
                else:
                    summaries[name] = {"keys": sorted(doc)[:8]}
            else:
                with open(p, encoding="utf-8") as f:
                    summaries[name] = {
                        "lines": sum(1 for _ in f),
                    }
        except (OSError, ValueError) as e:
            summaries[name] = {"error": str(e)}
    m["summaries"] = summaries
    return m


def export_incident(root: str, incident_id: str, out_path: str) -> str:
    """Pack one bundle directory into a ``.tar.gz`` at ``out_path``
    (the ``obs incident export`` payload — hand the whole incident to
    another human in one file)."""
    iid = _check_id(incident_id)
    src = os.path.join(root, iid)
    if not os.path.isfile(os.path.join(src, MANIFEST)):
        raise FileNotFoundError(f"no incident {iid!r} under {root!r}")
    with tarfile.open(out_path, "w:gz") as tar:
        tar.add(src, arcname=iid)
    return out_path
