"""``shifu_tpu obs top``: one pane of glass over a live router.

Polls ``GET /statz`` + ``GET /sloz`` and renders a plain-text frame —
tier burn rates/headroom on top, the sticky-session line (affinity
occupancy, warm-placement hit rate, migration counts — the /statz
``session`` block), then one row per backend (role, health, watchdog
reasons, load, prefix-cache occupancy) below. Deliberately
curses-free: the frame is a pure function of the two JSON documents
(``render_top``), so the chaos tests and a human terminal consume the
exact same rendering, and ``--once`` mode pipes cleanly into files.

``--loadgen REPORT.json`` adds the measurement block: the verdict,
offered-vs-achieved load, goodput and per-tier client percentiles
from a ``shifu_tpu loadgen --report`` file (re-read every frame, so
a watcher sees the latest finished run next to the live fleet).
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request
from typing import Optional

_CLEAR = "\x1b[H\x1b[2J"


def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _row(cols, widths) -> str:
    return "  ".join(
        str(c)[:w].ljust(w) for c, w in zip(cols, widths)
    ).rstrip()


def render_top(statz: dict, sloz: Optional[dict] = None,
               loadgen: Optional[dict] = None) -> str:
    """The dashboard frame for one poll of /statz (+ optional /sloz,
    + an optional loadgen verdict report). Pure: no I/O, no clock —
    testable against canned documents."""
    lines = []
    eng = statz.get("engine", {}) or {}
    lat = statz.get("latency", {}) or {}
    lines.append(
        "fleet: "
        f"slots {eng.get('active_slots', 0)}/{eng.get('max_slots', 0)}"
        f"  queued {eng.get('queued', 0)}"
        f"  completed {eng.get('requests_completed', 0)}"
        f"  batch {eng.get('batch_completed', 0)}"
        f"  retry-budget {eng.get('retry_budget', '-')}"
    )
    if lat.get("completions"):
        lines.append(
            f"latency: ttft p50/p99 {_fmt(lat.get('ttft_ms_p50'))}/"
            f"{_fmt(lat.get('ttft_ms_p99'))} ms"
            f"  itl p99 {_fmt(lat.get('req_itl_ms_p99'))} ms"
            f"  window {lat.get('completions')} reqs"
        )
    sess = statz.get("session") or {}
    if sess:
        reqs = sess.get("requests") or {}
        lines.append(
            "session: "
            f"affinity {sess.get('affinity_entries', 0)}/"
            f"{sess.get('affinity_slots', 0)}"
            f"  hit-rate {_fmt(sess.get('sticky_hit_rate'), 3)}"
            f"  sticky {reqs.get('sticky', 0)}"
            f"  migrated {reqs.get('migrated', 0)}"
            f"  rebalanced {reqs.get('rebalanced', 0)}"
            f"  migrations {sess.get('migrations', 0)}"
            f" (fail {sess.get('migrate_fallbacks', 0)}"
            f", breakeven {sess.get('migrate_breakeven_losses', 0)})"
        )
    ascale = statz.get("autoscale") or {}
    if ascale:
        # The elastic-fleet controller's /statz block: pool size, last
        # action, min per-tier headroom at the last decision, and the
        # envelope's utilization -> batch-admission scale.
        last = ascale.get("last_action") or {}
        env = ascale.get("envelope") or {}
        headroom = ascale.get("headroom")
        if headroom is None and last.get("headroom") is not None:
            headroom = last.get("headroom")
        acts = ascale.get("actions") or {}
        scale = ascale.get("admission_scale", env.get("scale"))
        util = env.get("util", ascale.get("admission_util"))
        lines.append(
            "autoscale: "
            f"pool {ascale.get('pool', '-')}"
            f"  status {ascale.get('status', '-')}"
            f"  last {last.get('action', '-')}"
            + (f" {last.get('backend')}" if last.get("backend") else "")
            + f"  headroom {_fmt(headroom, 2)}"
            f"  envelope {_fmt(util, 2)}"
            f"->{_fmt(scale, 2)}"
            f"  flips {acts.get('role_flip', 0)}"
            f" (fail {acts.get('scale_up_failed', 0)}"
            f"+{acts.get('role_flip_failed', 0)})"
        )
    peer = (statz.get("cache") or {}).get("peer") or {}
    if peer:
        # Content-addressed peer fetch totals (the router's /cachez
        # "peer" block): chains pulled over /kv/pages?digest=.
        lines.append(
            "peer-kv: "
            f"fetches {peer.get('fetches', 0)}"
            f"  pages {peer.get('pages', 0)}"
            f"  bytes {peer.get('bytes', 0)}"
            f"  warmups {peer.get('warmups', 0)}"
            f" (fail {peer.get('failures', 0)}"
            f", breakeven {peer.get('breakeven_losses', 0)})"
        )

    tiers = (sloz or {}).get("tiers") or {}
    if tiers:
        lines.append("")
        widths = (12, 9, 10, 10, 9)
        lines.append(_row(
            ("TIER", "STATUS", "BURN-FAST", "BURN-SLOW", "HEADROOM"),
            widths,
        ))
        for tier in sorted(tiers):
            d = tiers[tier]
            win = d.get("windows", {})
            lines.append(_row((
                tier,
                d.get("status", "-"),
                _fmt(win.get("fast", {}).get("burn_rate"), 2),
                _fmt(win.get("slow", {}).get("burn_rate"), 2),
                _fmt(d.get("headroom"), 2),
            ), widths))

    fleet = statz.get("fleet") or {}
    rows = fleet.get("backends") or []
    if rows:
        lines.append("")
        widths = (21, 7, 9, 9, 4, 6, 9, 8)
        lines.append(_row(
            ("BACKEND", "ROLE", "STATUS", "HEALTHZ", "INFL",
             "QUEUE", "EWMA-MS", "BREAKER"),
            widths,
        ))
        cache = (statz.get("cache") or {}).get("backends") or {}
        for r in rows:
            lines.append(_row((
                r.get("backend", "-"),
                r.get("role", "-"),
                r.get("status", "-"),
                r.get("healthz", "-"),
                r.get("in_flight", 0),
                r.get("queue_depth", 0),
                _fmt(r.get("ewma_ms")),
                r.get("breaker", "-"),
            ), widths))
            reasons = r.get("healthz_reasons") or ()
            for reason in reasons:
                lines.append(f"    ! {reason}")
            blk = cache.get(r.get("backend"))
            pc = (blk or {}).get("prefix_cache")
            if pc:
                # /cachez keys: registered_pages of n_pages total
                # (the occupancy the sticky score routes on).
                lines.append(
                    f"    cache: {pc.get('registered_pages', 0)}/"
                    f"{pc.get('n_pages', 0)} pages"
                    f"  occ {_fmt(r.get('cache_occupancy'), 3)}"
                    f"  hit-rate {_fmt(pc.get('hit_rate'), 3)}"
                )
            dt = (blk or {}).get("disk_tier")
            if dt:
                # /cachez disk_tier keys: the NVMe segment store below
                # the host tier (bytes, segments, hit/evict totals).
                lines.append(
                    f"    disk: {dt.get('segments', 0)} seg"
                    f"  {dt.get('bytes_used', 0)}/"
                    f"{dt.get('capacity_bytes', 0)} B"
                    f"  hits {dt.get('hits', 0)}"
                    f"  evict {dt.get('evictions', 0)}"
                    f"  torn {dt.get('torn_refused', 0)}"
                    f"  resumed {dt.get('resumed_segments', 0)}"
                )

    if loadgen:
        lines.append("")
        lines.append(
            f"loadgen: {loadgen.get('scenario', '-')}"
            f"  verdict {loadgen.get('verdict', '-')}"
            f"  offered {_fmt(loadgen.get('offered_rps'))} rps"
            f"  achieved {_fmt(loadgen.get('achieved_rps'))}"
            f"  goodput {_fmt(loadgen.get('goodput_rps'))}"
            f"  err {_fmt(loadgen.get('error_rate'), 4)}"
        )
        lg_tiers = loadgen.get("tiers") or {}
        if lg_tiers:
            widths = (12, 9, 9, 11, 11, 8)
            lines.append(_row(
                ("LG-TIER", "STATUS", "HEADROOM", "P50-TTFT",
                 "P99-TTFT", "REQS"), widths,
            ))
            for tier in sorted(lg_tiers):
                d = lg_tiers[tier]
                c = d.get("client", {}) or {}
                lines.append(_row((
                    tier,
                    d.get("status", "-"),
                    _fmt(d.get("headroom"), 2),
                    _fmt(c.get("p50_ttft_ms")),
                    _fmt(c.get("p99_ttft_ms")),
                    c.get("requests", 0),
                ), widths))
        chaos = loadgen.get("chaos") or ()
        for ev in chaos:
            lines.append(
                f"    chaos @{_fmt(ev.get('at_s'))}s "
                f"{ev.get('action', '-')} {ev.get('target') or ''} "
                f"-> {ev.get('outcome', '-')}"
            )
    return "\n".join(lines) + "\n"


def _fetch(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read())


def run_top(url: str, *, interval_s: float = 2.0,
            iterations: Optional[int] = None, out=None,
            timeout_s: float = 10.0,
            loadgen_path: Optional[str] = None) -> int:
    """Poll-and-render loop (``iterations=None`` = until ^C; ``1`` is
    the ``--once`` mode). ``loadgen_path`` names a loadgen verdict
    report re-read each frame. Returns a CLI exit code."""
    out = out if out is not None else sys.stdout
    base = url.rstrip("/")
    n = 0
    while iterations is None or n < iterations:
        try:
            statz = _fetch(base + "/statz", timeout_s)
        except (OSError, ValueError) as e:
            print(f"cannot fetch {base}/statz: {e}", file=sys.stderr)
            return 2
        try:
            sloz = _fetch(base + "/sloz", timeout_s)
        except (OSError, ValueError):
            sloz = None  # pre-/sloz server: dashboard still works
        lg = None
        if loadgen_path:
            try:
                with open(loadgen_path, encoding="utf-8") as f:
                    lg = json.load(f)
            except (OSError, ValueError):
                lg = None  # report not written yet: block stays off
        frame = render_top(statz, sloz, loadgen=lg)
        if iterations != 1:
            out.write(_CLEAR)
        out.write(frame)
        out.flush()
        n += 1
        if iterations is None or n < iterations:
            try:
                time.sleep(interval_s)
            except KeyboardInterrupt:
                break
    return 0
