"""Observability: metrics registry, Prometheus exposition, request tracing.

Dependency-free (stdlib only) and cheap enough to update on the engine
thread per step. One process-global :data:`REGISTRY` is the default sink
for every subsystem — the serving engines, the HTTP server, the training
loop, and the bench all write to it, so ``GET /metrics`` and the train
JSONL log are two views of one source of truth. Tests (or embedders that
want isolation) construct their own :class:`MetricsRegistry` and pass it
via ``Engine(metrics=...)`` / ``MetricsLogger(registry=...)``.

Modules:

``registry``  counters / gauges / fixed-bucket histograms with labels,
              the Prometheus text-exposition renderer, a JSON snapshot,
              histogram quantile estimation, and a text-format parser
              (used by tests and the driver's dryrun scrape).
``trace``     per-request span records -> Chrome trace-event JSON
              (``shifu_tpu trace export``), complementing the
              device-side ``jax.profiler`` traces with host wall-clock
              queue -> prefill -> decode spans.
"""

from shifu_tpu.obs.registry import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    parse_exposition,
)
from shifu_tpu.obs.trace import chrome_trace, export_trace_log

# The process-global default registry (see module docstring).
REGISTRY = MetricsRegistry()

__all__ = [
    "DEFAULT_BUCKETS",
    "MetricsRegistry",
    "REGISTRY",
    "chrome_trace",
    "export_trace_log",
    "parse_exposition",
]
