"""Observability: metrics, tracing, flight recorder, SLO watchdog.

Dependency-free (stdlib only; the compile/HBM telemetry imports jax
lazily inside its functions) and cheap enough to update on the engine
thread per step. One process-global :data:`REGISTRY` is the default
metrics sink and one process-global :data:`FLIGHT` ring the default
event sink for every subsystem — the serving engines, the HTTP server,
the training loop, and the bench all write to them, so ``GET
/metrics``, ``GET /debugz``, and the train JSONL log are views of one
source of truth. Tests (or embedders that want isolation) construct
their own :class:`MetricsRegistry` / :class:`FlightRecorder` and pass
them via ``Engine(metrics=..., flight=...)``.

Modules:

``registry``   counters / gauges / fixed-bucket histograms with labels,
               the Prometheus text-exposition renderer, a JSON snapshot,
               histogram quantile estimation, and a text-format parser
               (used by tests and the driver's dryrun scrape).
``trace``      per-request span records -> Chrome trace-event JSON
               (``shifu_tpu trace export``), complementing the
               device-side ``jax.profiler`` traces with host wall-clock
               queue -> prefill -> decode spans.
``flight``     fixed-size ring of structured runtime events (engine
               steps, compiles, preemptions, NaN-skips, crashes) —
               ``GET /debugz``, ``shifu_tpu debug dump``, and the
               runner's crash auto-dump read it.
``watchdog``   declared SLO budgets (p99 TTFT/ITL, step time, queue
               depth) evaluated over sliding windows; flips ``/healthz``
               to "degraded" with reason strings. Budgets also apply to
               a router's FEDERATED (fleet-pooled) histograms when the
               engine exposes ``federated_quantile``.
``disttrace``  fleet-wide distributed tracing: the ``x-shifu-trace``
               context (mint/parse/propagate), bounded per-trace span
               stores behind ``GET /tracez``, NTP-style clock-offset
               estimation from prober round trips, cross-host trace
               merging into one Chrome trace, and /metrics federation
               (``shifu_fleet_agg_*``).
``slo``        the FLEET SLO engine: per-tier (interactive/batch)
               burn-rate budgets — p99 TTFT/ITL + error rate — over
               fast/slow windows of the federated metrics pool,
               serving ``GET /sloz`` (status / burn_rate / headroom)
               and the ``shifu_slo_burn_rate{tier,window}`` gauges.
``incident``   cross-host incident bundles: on an SLO breach the
               router freezes every backend's /debugz ring, the merged
               recent traces, and a federated metrics snapshot into a
               timestamped directory with a manifest (rate-limited;
               ``shifu_tpu obs incident list|show|export``).
``top``        ``shifu_tpu obs top``: a live /statz + /sloz terminal
               dashboard (pure-function frame rendering, curses-free).
``compilemon`` compile telemetry (per-jitted-function recompile
               counters/latencies + the jax.monitoring mirror) and
               sampled HBM gauges.
``benchgate``  bench regression gate: compact-line vs recorded baseline
               within declared per-metric tolerances (``bench.py
               --baseline`` / ``shifu_tpu obs check-bench``).
"""

from shifu_tpu.obs.registry import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    parse_exposition,
)
from shifu_tpu.obs.trace import chrome_trace, export_trace_log
from shifu_tpu.obs.flight import FLIGHT, FlightRecorder
from shifu_tpu.obs.watchdog import SLOConfig, SLOWatchdog
from shifu_tpu.obs.disttrace import (
    ClockSync,
    SpanStore,
    TraceContext,
    ensure_context,
    fetch_and_merge,
    merge_host_docs,
    parse_header,
)
from shifu_tpu.obs.slo import (
    SLOEngine,
    SLOMonitor,
    TierBudget,
    parse_budget_spec,
)
from shifu_tpu.obs.incident import IncidentWriter

# The process-global default registry (see module docstring).
REGISTRY = MetricsRegistry()

__all__ = [
    "ClockSync",
    "DEFAULT_BUCKETS",
    "FLIGHT",
    "FlightRecorder",
    "IncidentWriter",
    "MetricsRegistry",
    "REGISTRY",
    "SLOConfig",
    "SLOEngine",
    "SLOMonitor",
    "SLOWatchdog",
    "SpanStore",
    "TierBudget",
    "TraceContext",
    "chrome_trace",
    "ensure_context",
    "export_trace_log",
    "fetch_and_merge",
    "merge_host_docs",
    "parse_budget_spec",
    "parse_exposition",
    "parse_header",
]
