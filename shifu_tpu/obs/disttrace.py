"""Fleet-wide distributed tracing: context propagation, clock
alignment, cross-host trace assembly, and /metrics federation.

A request that crosses the router, a backend queue, prefill, decode,
and maybe a resubmit after a backend death can only be explained if
every hop carries ONE identity. This module provides the pieces; the
router, engine, server, and batch runner wire them in:

``TraceContext``    trace_id / span_id / parent_id, minted at the edge
                    (router, or the engine server when hit directly)
                    and propagated over the existing HTTP surface via
                    the ``x-shifu-trace`` header (``HEADER``), format
                    ``<trace_id>-<span_id>[-<parent_id>]``, lowercase
                    hex. Each hop forwards a ``child()`` so the parent
                    chain survives the wire.

``SpanStore``       bounded per-trace span records (engine completions,
                    router hops, resubmits) backing ``GET
                    /tracez?trace_id=``. Records are plain dicts in the
                    trace-log JSONL shape; ``t0_ms`` is on the OWNING
                    host's monotonic clock.

``ClockSync``       NTP-style offset estimation from the probe round
                    trips the FleetProber already makes: one sample is
                    ``offset = remote_wall - (t0 + t1) / 2`` with error
                    bound ``rtt / 2``; the minimum-RTT sample wins (a
                    congested probe can only widen the bound, never
                    flip its sign past rtt/2).

``merge_host_docs`` per-host span documents -> ONE Chrome trace with a
                    lane per (host, replica). Each doc carries paired
                    ``mono_now_ms`` / ``wall_now_ms`` stamps so records
                    move monotonic -> that host's wall clock, then the
                    probe-estimated ``offset_ms`` moves them onto the
                    collector's wall clock.

``federate``        per-backend Prometheus scrapes -> one text block of
                    ``shifu_fleet_agg_*`` families: counters and gauges
                    summed, histograms pooled bucket-wise (the parsed
                    samples are cumulative, so summing per ``le`` edge
                    across backends is exact), per-backend series kept
                    under a ``backend`` label next to the pooled ones.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from shifu_tpu.obs.registry import _bucket_quantile, escape_label_value
from shifu_tpu.obs.trace import chrome_trace

# The one propagation header. Lowercase (http.client titlecases on the
# wire; BaseHTTPRequestHandler matching is case-insensitive).
HEADER = "x-shifu-trace"

AGG_PREFIX = "shifu_fleet_agg_"

_ID_RE = re.compile(r"^[0-9a-f]{2,32}$")


def _gen_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's identity within a trace. ``trace_id`` is constant for
    the request's whole life (resubmits included); ``span_id`` names
    this hop; ``parent_id`` names the hop that forwarded to us."""

    trace_id: str
    span_id: str
    parent_id: str = ""

    def child(self) -> "TraceContext":
        """The context to forward downstream: same trace, fresh span,
        this hop as the parent."""
        return TraceContext(self.trace_id, _gen_id(8), self.span_id)

    def to_header(self) -> str:
        if self.parent_id:
            return f"{self.trace_id}-{self.span_id}-{self.parent_id}"
        return f"{self.trace_id}-{self.span_id}"

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        return d


def mint() -> TraceContext:
    """A fresh root context (32-hex trace id, 16-hex span id)."""
    return TraceContext(_gen_id(16), _gen_id(8))


def parse_header(value) -> Optional[TraceContext]:
    """``x-shifu-trace`` header value -> context, or None when absent
    or malformed (a garbled header must not fail the request — the
    caller mints a fresh root instead)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) not in (2, 3):
        return None
    if not all(_ID_RE.match(p) for p in parts):
        return None
    return TraceContext(*parts)


def ensure_context(header_value=None) -> TraceContext:
    """Parse the inbound header or mint a root — the edge-of-process
    entry point (HTTP handler, batch runner line, router submit)."""
    ctx = parse_header(header_value)
    return ctx if ctx is not None else mint()


# --------------------------------------------------------------- spans
class SpanStore:
    """Bounded per-trace span records backing ``GET /tracez``.

    One ``add`` is a lock + two dict/list ops — cheap enough for the
    completion path (per request, not per token). Traces evict oldest-
    inserted once ``max_traces`` is reached, records per trace are
    capped at ``max_spans`` (a runaway retry loop must not grow without
    bound)."""

    def __init__(self, max_traces: int = 256, max_spans: int = 128):
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans)
        self._traces: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def add(self, trace_id, rec: dict) -> None:
        tid = str(trace_id or "")
        if not tid:
            return
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                while len(self._traces) >= self.max_traces:
                    self._traces.popitem(last=False)
                spans = self._traces[tid] = []
            if len(spans) < self.max_spans:
                spans.append(rec)

    def get(self, trace_id) -> List[dict]:
        with self._lock:
            return list(self._traces.get(str(trace_id or ""), ()))

    def recent(self, n: int = 3) -> List[str]:
        """The last ``n`` trace ids by insertion order, newest first —
        the incident-bundle capture's "what just happened" selection
        (obs/incident.py merges these across hosts)."""
        with self._lock:
            ids = list(self._traces)
        return ids[::-1][:max(int(n), 0)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


def span_record(kind: str, ctx: Optional[TraceContext], t0_ms: float,
                dur_ms: float, **fields) -> dict:
    """A generic (non-engine-timing) span record in the trace-log
    shape: ``t0_ms`` on the recording host's monotonic clock."""
    rec = {
        "kind": str(kind),
        "t0_ms": float(t0_ms),
        "dur_ms": max(float(dur_ms), 0.0),
    }
    if ctx is not None:
        rec.update(ctx.to_dict())
    rec.update(fields)
    return rec


def host_doc(host: str, records: Iterable[dict], *,
             replica: Optional[str] = None,
             offset_ms: float = 0.0, err_ms: float = 0.0) -> dict:
    """One host's contribution to a /tracez response. The paired
    monotonic/wall stamps are taken HERE, in the process that owns the
    records' monotonic clock — that pairing is what lets the collector
    convert ``t0_ms`` to this host's wall clock."""
    doc = {
        "host": str(host),
        "mono_now_ms": time.monotonic() * 1000.0,
        "wall_now_ms": time.time() * 1000.0,
        "offset_ms": float(offset_ms),
        "err_ms": float(err_ms),
        "records": list(records),
    }
    if replica is not None:
        doc["replica"] = str(replica)
    return doc


# ------------------------------------------------------ clock alignment
def probe_offset(t0_ms: float, t1_ms: float,
                 remote_wall_ms: float) -> Tuple[float, float]:
    """One NTP-style sample from a probe round trip: the remote stamped
    its wall clock somewhere inside [t0, t1] on our clock, so ``offset
    = remote - midpoint`` is wrong by at most ``rtt / 2``."""
    rtt = max(float(t1_ms) - float(t0_ms), 0.0)
    offset = float(remote_wall_ms) - (float(t0_ms) + float(t1_ms)) / 2.0
    return offset, rtt / 2.0


class ClockSync:
    """Best (minimum-RTT) offset sample per peer, refreshed when a
    sample at least as tight arrives or the held one goes stale
    (clocks drift; a tight sample from ten minutes ago can be worse
    than a loose fresh one)."""

    STALE_S = 120.0

    def __init__(self):
        self._best: Dict[str, Tuple[float, float, float]] = {}
        self._lock = threading.Lock()

    def note(self, peer: str, t0_ms: float, t1_ms: float,
             remote_wall_ms) -> None:
        if not isinstance(remote_wall_ms, (int, float)):
            return
        offset, err = probe_offset(t0_ms, t1_ms, remote_wall_ms)
        now = time.monotonic()
        with self._lock:
            held = self._best.get(peer)
            if (held is None or err <= held[1]
                    or now - held[2] > self.STALE_S):
                self._best[peer] = (offset, err, now)

    def offset(self, peer: str) -> Tuple[float, float]:
        """(offset_ms, err_ms); (0, inf) for a never-probed peer —
        the merge still works, just without a cross-host guarantee."""
        with self._lock:
            held = self._best.get(peer)
        if held is None:
            return 0.0, math.inf
        return held[0], held[1]


# -------------------------------------------------------- trace merge
def merge_host_docs(docs: Iterable[dict], *,
                    trace_id: Optional[str] = None) -> dict:
    """Per-host span documents -> one merged Chrome trace.

    Each record's ``t0_ms`` is on its host's monotonic clock. The shift
    to the collector's wall clock is ``(wall_now - mono_now) -
    offset``: the paired stamps move monotonic -> that host's wall
    clock, and ``offset_ms`` (= remote_wall - collector_wall from the
    probe midpoint) moves that onto the collector's. Lane assignment —
    one process lane per (host, replica) — is chrome_trace's job."""
    merged: List[dict] = []
    worst_err = 0.0
    hosts = []
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        host = str(doc.get("host") or "local")
        if host not in hosts:
            hosts.append(host)
        shift = (
            float(doc.get("wall_now_ms", 0.0))
            - float(doc.get("mono_now_ms", 0.0))
            - float(doc.get("offset_ms", 0.0))
        )
        err = doc.get("err_ms", 0.0)
        if isinstance(err, (int, float)) and math.isfinite(err):
            worst_err = max(worst_err, float(err))
        for rec in doc.get("records", ()):
            if not isinstance(rec, dict):
                continue
            if trace_id is not None and rec.get("trace_id") != trace_id:
                continue
            r = dict(rec)
            r["t0_ms"] = float(r.get("t0_ms", 0.0)) + shift
            r.setdefault("host", host)
            if "replica" not in r and doc.get("replica") is not None:
                r["replica"] = doc["replica"]
            merged.append(r)
    merged.sort(key=lambda r: r["t0_ms"])
    trace = chrome_trace(merged)
    trace["otherData"].update(
        hosts=hosts,
        align_err_ms=worst_err,
        **({"trace_id": trace_id} if trace_id else {}),
    )
    return trace


def fetch_and_merge(url: str, trace_id: str, *,
                    timeout_s: float = 10.0) -> dict:
    """``GET {url}/tracez?trace_id=`` on a router (or single backend)
    and merge the returned host docs — the ``shifu_tpu trace export
    --url --trace-id`` implementation."""
    import json as _json
    from urllib.parse import quote
    from urllib.request import urlopen

    base = url.rstrip("/")
    full = f"{base}/tracez?trace_id={quote(str(trace_id))}"
    with urlopen(full, timeout=timeout_s) as resp:
        doc = _json.loads(resp.read().decode("utf-8"))
    return merge_host_docs(doc.get("hosts", ()), trace_id=str(trace_id))


# ---------------------------------------------------------- federation
def federate(parsed_by_backend: Dict[str, Dict[tuple, float]],
             ) -> Tuple[str, Dict[tuple, float]]:
    """Per-backend parsed scrapes -> (federated exposition text, pooled
    samples).

    Input is ``{backend_addr: parse_exposition(text)}``. Every
    ``shifu_*`` sample becomes TWO series under ``shifu_fleet_agg_`` +
    the name minus its ``shifu_`` prefix: one per-backend (original
    labels plus ``backend``) and one pooled (original labels, values
    summed across backends). Histogram ``_bucket`` samples are
    cumulative counts, so the per-``le`` sum across backends is the
    exact pooled histogram. Already-federated families are skipped so a
    router scraping a router does not double-count."""
    pooled: Dict[tuple, float] = {}
    per_backend: Dict[tuple, float] = {}
    for addr in sorted(parsed_by_backend):
        for (name, labels), val in parsed_by_backend[addr].items():
            if not name.startswith("shifu_") or name.startswith(AGG_PREFIX):
                continue
            agg = AGG_PREFIX + name[len("shifu_"):]
            if not math.isfinite(val):
                continue
            per_backend[(agg, labels | {("backend", addr)})] = val
            key = (agg, labels)
            pooled[key] = pooled.get(key, 0.0) + val
    lines = []
    for samples in (pooled, per_backend):
        for (name, labels) in sorted(
            samples, key=lambda k: (k[0], sorted(k[1]))
        ):
            lbl = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in sorted(labels)
            )
            v = samples[(name, labels)]
            sv = str(int(v)) if float(v).is_integer() else repr(float(v))
            lines.append(f"{name}{{{lbl}}} {sv}" if lbl else f"{name} {sv}")
    return ("\n".join(lines) + "\n" if lines else ""), pooled


def quantile_from_pooled(pooled: Dict[tuple, float], family: str,
                         q: float,
                         labels: Optional[dict] = None) -> Optional[float]:
    """Estimated quantile over a pooled federated histogram family
    (``family`` WITHOUT the agg prefix, e.g. ``shifu_request_ttft_
    seconds``), pooling every series whose labels are a superset of
    ``labels`` — the fleet-wide view the SLO watchdog budgets on."""
    name = family
    if name.startswith("shifu_") and not name.startswith(AGG_PREFIX):
        name = AGG_PREFIX + name[len("shifu_"):]
    bucket_name = name + "_bucket"
    want = {k: str(v) for k, v in (labels or {}).items()}
    acc: Dict[float, float] = {}
    for (sname, slabels), val in pooled.items():
        if sname != bucket_name:
            continue
        ld = dict(slabels)
        le = ld.pop("le", None)
        if le is None:
            continue
        if any(ld.get(k) != v for k, v in want.items()):
            continue
        edge = math.inf if le in ("+Inf", "inf") else float(le)
        acc[edge] = acc.get(edge, 0.0) + val
    if not acc:
        return None
    edges = tuple(sorted(e for e in acc if e != math.inf))
    # Cumulative-per-edge -> per-bucket counts (+Inf last).
    cum = [acc[e] for e in edges]
    inf_cum = acc.get(math.inf, cum[-1] if cum else 0.0)
    counts, prev = [], 0.0
    for c in cum:
        counts.append(max(c - prev, 0.0))
        prev = c
    counts.append(max(inf_cum - prev, 0.0))
    total = sum(counts)
    return _bucket_quantile(edges, counts, total, q)
