"""Flight recorder: a fixed-size ring of structured runtime events.

The black box the serving/train runtimes write their last-K step-level
events into — engine step phases, queue depth, slot occupancy, compile
events, NaN-skips, preemptions, crashes. Appends are O(1) and allocate
one small dict, cheap enough for the engine thread per step; the ring
is bounded so a long-lived server's forensics cost is constant.

Read surfaces:

  * ``GET /debugz`` on the serving front-end returns the ring
    (infer/server.py);
  * ``shifu_tpu debug dump`` fetches it from a live server or dumps the
    in-process ring (cli.py);
  * on engine-thread death the runner auto-dumps the ring to disk
    (``EngineRunner(flight_dump=...)``) so a crash leaves forensics
    instead of nothing;
  * the SLO watchdog reads the recent ``step`` events' durations for
    its step-time budget (obs/watchdog.py).

One process-global :data:`FLIGHT` ring is the default sink (mirroring
``obs.REGISTRY``); engines accept ``flight=`` for isolation in tests.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import List, Optional


class FlightRecorder:
    """Bounded ring of event dicts. Thread-safe: the engine thread
    appends; HTTP scrape threads snapshot. ``deque.append`` is atomic
    under the GIL, but ``snapshot`` still locks against a concurrent
    append mutating the deque mid-``list()``."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        # Events pushed out of the ring (how much history was lost) —
        # lets a reader tell "quiet server" from "ring wrapped".
        self.dropped = 0

    def record(self, kind: str, **fields) -> None:
        """Append one event. ``fields`` must be JSON-serializable
        scalars (the ring feeds /debugz and crash dumps verbatim)."""
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(ev)

    def snapshot(self, last: Optional[int] = None,
                 kind: Optional[str] = None,
                 trace_id: Optional[str] = None) -> List[dict]:
        """The ring's events, oldest first; optionally only the
        ``last`` N, optionally filtered to one ``kind`` and/or one
        distributed ``trace_id`` (request completions carry it when the
        request had a trace context). Filters apply BEFORE the tail
        cut, so ``last`` counts matching events."""
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e.get("kind") == kind]
        if trace_id is not None:
            events = [e for e in events if e.get("trace_id") == trace_id]
        if last is not None and last >= 0:
            events = events[-last:]
        return events

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def dump(self, path: str, extra: Optional[dict] = None) -> str:
        """Write the ring (plus optional context, e.g. the crash error)
        to ``path`` as one JSON document. Returns the path."""
        doc = {
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "dropped": self.dropped,
            "events": self.snapshot(),
        }
        if extra:
            doc["extra"] = extra
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        return path


# The process-global default ring (see module docstring).
FLIGHT = FlightRecorder()
