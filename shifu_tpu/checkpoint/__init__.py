"""Checkpoint / resume subsystem.

Sharding-aware save + restore of the full training state, built on orbax
(the TPU-native checkpoint stack): every host writes only its own parameter
shards, restore places each shard directly onto its owning devices (no
host-side full copy), and saves run asynchronously so the step loop is not
blocked on HBM->disk transfers.

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md) — there is no reference checkpoint format to match.
The format here is orbax's standard OCDBT + zarr3 layout.
"""

from shifu_tpu.checkpoint.checkpointer import (
    Checkpointer,
    CheckpointCorruptError,
    abstract_train_state,
    load_params_dir,
    load_serving_params,
    save_params_dir,
    verify_params_dir,
)

__all__ = [
    "Checkpointer",
    "CheckpointCorruptError",
    "abstract_train_state",
    "load_params_dir",
    "load_serving_params",
    "save_params_dir",
    "verify_params_dir",
]
