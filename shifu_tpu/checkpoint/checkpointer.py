"""Sharding-aware checkpointing on orbax + the serving params format.

Three pieces:

  * :func:`abstract_train_state` — builds the restore *template*: a
    TrainState-shaped tree of ``jax.ShapeDtypeStruct`` leaves carrying
    NamedShardings (when a mesh is given). Restoring against the template
    materialises every weight directly into its shards — the checkpoint can
    be larger than any single host's memory.
  * :class:`Checkpointer` — thin lifecycle wrapper over
    ``orbax.checkpoint.CheckpointManager``: async saves, retention,
    save-interval gating, and a JSON side-channel for host state (data
    iterator position, python RNG, config fingerprints, ...).
  * the MANIFEST params format (:func:`save_params_dir` /
    :func:`load_params_dir`) — a params-only serving checkpoint with
    per-array sha256 checksums, written all-or-nothing (files land in a
    temp dir, the manifest is fsynced + atomically renamed into place
    LAST, then the whole dir renames to its final name). A torn,
    truncated, or bit-flipped checkpoint fails :func:`load_params_dir`
    with :class:`CheckpointCorruptError` BEFORE any weight reaches an
    engine — the hot-reload path (``POST /reloadz``, ``shifu_tpu fleet
    rollout``) turns that into a loud 503 with the backend still
    serving its old weights, never a half-swapped model.

Design choices (TPU-first):
  * Saves are async by default: the save() call snapshots device buffers to
    host memory and returns; serialisation/writes overlap the next steps.
    ``wait()`` (or ``close()``) joins the writer — call before process exit.
  * The train step counter lives *inside* the state (TrainState.opt["step"]),
    so "which step is this checkpoint" is read off the state itself; the
    manager's step index is only a directory label.
  * :func:`load_serving_params` is the ONE loader the reload path uses:
    a manifest dir (``manifest.json`` present) loads checksum-verified;
    anything else is treated as an orbax checkpoint dir and read via
    :meth:`Checkpointer.restore_params` (orbax's own atomic-commit
    markers gate completeness there; produce manifest dirs with
    ``shifu_tpu fleet snapshot`` for the verified path).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from shifu_tpu.parallel import sharding as shd
from shifu_tpu.train.step import TrainState

MANIFEST_NAME = "manifest.json"
_MANIFEST_FORMAT = "shifu-params-v1"


class CheckpointCorruptError(RuntimeError):
    """A manifest params checkpoint failed integrity verification
    (missing/unparseable manifest, missing array file, byte-count or
    sha256 mismatch). The loader raises BEFORE returning any array —
    callers keep whatever weights they already serve."""


def abstract_train_state(model, mesh=None, rules=shd.DEFAULT_RULES, optimizer=None):
    """TrainState template of ShapeDtypeStructs for sharded restore.

    Mirrors exactly what ``create_sharded_state(model, optimizer, ...)``
    produces — the optimizer's ``state_template`` defines the opt-state
    structure (``optimizer=None`` defaults to AdamW's mu/nu/step layout).
    With ``mesh=None`` the leaves carry no sharding (single-process
    restore).
    """
    from shifu_tpu.train.optimizer import AdamW

    optimizer = AdamW() if optimizer is None else optimizer
    scalar = (
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        if mesh is not None
        else None
    )
    params_tmpl = shd.abstract_params(model, mesh, rules)
    opt = optimizer.state_template(
        params_tmpl, jax.ShapeDtypeStruct((), jnp.int32, sharding=scalar)
    )
    return TrainState(params=params_tmpl, opt=opt)


class Checkpointer:
    """Manage a directory of step-indexed checkpoints.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3, save_interval_steps=1000)
        ckpt.save(step, state, host_state={"batches_seen": n})   # async
        ...
        # pass the SAME optimizer used for training — the restore template's
        # opt-state structure comes from it (AdamW if omitted)
        template = abstract_train_state(model, mesh, optimizer=opt)
        state, host = ckpt.restore(template)                      # latest
        ckpt.close()
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory), options=options
        )

    # ------------------------------------------------------------------ save
    def save(
        self,
        step: int,
        state: TrainState,
        host_state: Optional[Mapping[str, Any]] = None,
        *,
        force: bool = False,
    ) -> bool:
        """Queue a checkpoint. Returns False when gated by the interval."""
        return self._mgr.save(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                host=ocp.args.JsonSave(dict(host_state or {})),
            ),
            force=force,
        )

    # --------------------------------------------------------------- restore
    def restore(self, template: TrainState, step: Optional[int] = None):
        """Restore (state, host_state) at ``step`` (default: latest).

        ``template`` is a concrete TrainState or the output of
        :func:`abstract_train_state`; leaf shardings (when present) place
        shards straight onto devices.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        out = self._mgr.restore(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                host=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["host"]

    def restore_params(self, model, step: Optional[int] = None, *,
                       mesh=None, rules=shd.DEFAULT_RULES):
        """Restore ONLY the params subtree (partial read).

        For eval/serving: reads ~1/3 of an AdamW checkpoint's bytes (no
        optimizer moments) and needs no knowledge of which optimizer
        trained it. ``model`` provides the params template via its specs.

        Call on a FRESH Checkpointer: orbax pins one restore-handler type
        per item per manager, so mixing with save()/restore() on the same
        instance raises a handler-registry error.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        template = {"params": shd.abstract_params(model, mesh, rules)}
        out = self._mgr.restore(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.PyTreeRestore(
                    item=template, partial_restore=True
                ),
            ),
        )
        return out["state"]["params"]

    # ------------------------------------------------------------- inventory
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    # -------------------------------------------------------------- lifecycle
    def wait(self):
        """Block until queued async saves are durable on disk."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------------------------------
# Manifest params format: the serving/rollout checkpoint artifact.
# --------------------------------------------------------------------------
def _leaf_key(path) -> str:
    """jax key-path -> "/"-joined string key (params are nested dicts of
    arrays, so every entry is a DictKey; anything else is refused — the
    format round-trips plain dict trees only)."""
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if not isinstance(key, str) or "/" in key:
            raise ValueError(
                f"params tree key {p!r} is not a plain string dict key; "
                "the manifest format stores nested-dict param trees only"
            )
        parts.append(key)
    return "/".join(parts)


def _np_dtype(name: str) -> np.dtype:
    """Dtype string -> numpy dtype, covering the ml_dtypes extras
    (bfloat16 etc.) jax params commonly carry."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always importable with jax

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise ValueError(f"unknown array dtype {name!r}") from None


def _fsync_write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def save_params_dir(directory: str, params) -> str:
    """Write ``params`` (a nested dict tree of arrays) as a manifest
    params checkpoint at ``directory``. All-or-nothing: arrays land in
    a same-filesystem temp dir, the manifest (per-array file name,
    shape, dtype, byte count, sha256) is fsynced and atomically renamed
    into place last, then the temp dir renames to ``directory`` — a
    crash at any point leaves either no checkpoint or a complete one,
    never a torn dir that looks loadable. Refuses an existing target
    (checkpoints are immutable artifacts; write a new path per
    rollout)."""
    directory = os.path.abspath(directory)
    if os.path.exists(directory):
        raise FileExistsError(
            f"{directory} already exists; manifest checkpoints are "
            "immutable — write each rollout to a fresh path"
        )
    parent = os.path.dirname(directory) or "."
    os.makedirs(parent, exist_ok=True)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    if not leaves:
        raise ValueError("params tree has no arrays")
    tmp = tempfile.mkdtemp(
        prefix=os.path.basename(directory) + ".tmp.", dir=parent
    )
    try:
        arrays = {}
        for i, (path, leaf) in enumerate(leaves):
            key = _leaf_key(path)
            arr = np.asarray(jax.device_get(leaf))
            data = arr.tobytes()
            fname = f"{i:05d}.bin"
            _fsync_write(os.path.join(tmp, fname), data)
            arrays[key] = {
                "file": fname,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "nbytes": len(data),
                "sha256": hashlib.sha256(data).hexdigest(),
            }
        manifest = {"format": _MANIFEST_FORMAT, "arrays": arrays}
        # Manifest last, via temp-file + atomic rename: its presence is
        # the commit marker for the files around it.
        mtmp = os.path.join(tmp, MANIFEST_NAME + ".tmp")
        _fsync_write(
            mtmp, json.dumps(manifest, sort_keys=True).encode()
        )
        os.replace(mtmp, os.path.join(tmp, MANIFEST_NAME))
        os.rename(tmp, directory)
    except BaseException:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return directory


def verify_params_dir(directory: str) -> dict:
    """Integrity-check a manifest params checkpoint; returns the parsed
    manifest. Raises :class:`CheckpointCorruptError` on a missing or
    unparseable manifest, a missing array file, or any byte-count /
    sha256 mismatch — the torn-write and bit-rot detector the reload
    path trusts."""
    mpath = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read())
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{directory}: no {MANIFEST_NAME} — torn write or not a "
            "manifest params checkpoint"
        ) from None
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{directory}: unreadable manifest: {e}"
        ) from e
    if manifest.get("format") != _MANIFEST_FORMAT:
        raise CheckpointCorruptError(
            f"{directory}: manifest format {manifest.get('format')!r} "
            f"!= {_MANIFEST_FORMAT!r}"
        )
    arrays = manifest.get("arrays")
    if not isinstance(arrays, dict) or not arrays:
        raise CheckpointCorruptError(f"{directory}: manifest lists no arrays")
    for key, meta in arrays.items():
        fpath = os.path.join(directory, meta["file"])
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"{directory}: array {key!r} unreadable: {e}"
            ) from e
        if len(data) != int(meta["nbytes"]):
            raise CheckpointCorruptError(
                f"{directory}: array {key!r} truncated "
                f"({len(data)} bytes != {meta['nbytes']})"
            )
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta["sha256"]:
            raise CheckpointCorruptError(
                f"{directory}: array {key!r} checksum mismatch "
                f"({digest[:12]}… != {meta['sha256'][:12]}…)"
            )
    return manifest


def load_params_dir(directory: str):
    """Load a manifest params checkpoint, verifying EVERY array's byte
    count and sha256 first (:func:`verify_params_dir`) — corruption
    raises before a single weight is materialised. Returns the nested
    params dict (host numpy arrays; engines place/cast on swap)."""
    manifest = verify_params_dir(directory)
    out: dict = {}
    for key, meta in manifest["arrays"].items():
        with open(os.path.join(directory, meta["file"]), "rb") as f:
            data = f.read()
        arr = np.frombuffer(
            data, dtype=_np_dtype(meta["dtype"])
        ).reshape(meta["shape"])
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def load_serving_params(path: str, model=None):
    """Params for serving/hot-reload from ``path`` — the ONE loader
    behind ``POST /reloadz`` and ``shifu_tpu fleet rollout``.

    A manifest params dir (``manifest.json`` present) loads checksum-
    verified; any other existing directory is treated as an orbax
    checkpoint dir and read through :meth:`Checkpointer.restore_params`
    (``model`` supplies the params template — required on that path).
    Missing paths raise FileNotFoundError; corruption raises
    :class:`CheckpointCorruptError`."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint path {path} does not exist")
    if os.path.exists(os.path.join(path, MANIFEST_NAME)):
        return load_params_dir(path)
    if model is None:
        raise ValueError(
            f"{path} is an orbax checkpoint dir; restoring needs the "
            "model template (manifest params dirs do not)"
        )
    ckpt = Checkpointer(path)
    try:
        return ckpt.restore_params(model)
    finally:
        ckpt.close()
