"""Sharding-aware checkpointing on orbax.

Two pieces:

  * :func:`abstract_train_state` — builds the restore *template*: a
    TrainState-shaped tree of ``jax.ShapeDtypeStruct`` leaves carrying
    NamedShardings (when a mesh is given). Restoring against the template
    materialises every weight directly into its shards — the checkpoint can
    be larger than any single host's memory.
  * :class:`Checkpointer` — thin lifecycle wrapper over
    ``orbax.checkpoint.CheckpointManager``: async saves, retention,
    save-interval gating, and a JSON side-channel for host state (data
    iterator position, python RNG, config fingerprints, ...).

Design choices (TPU-first):
  * Saves are async by default: the save() call snapshots device buffers to
    host memory and returns; serialisation/writes overlap the next steps.
    ``wait()`` (or ``close()``) joins the writer — call before process exit.
  * The train step counter lives *inside* the state (TrainState.opt["step"]),
    so "which step is this checkpoint" is read off the state itself; the
    manager's step index is only a directory label.
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from shifu_tpu.parallel import sharding as shd
from shifu_tpu.train.step import TrainState


def abstract_train_state(model, mesh=None, rules=shd.DEFAULT_RULES, optimizer=None):
    """TrainState template of ShapeDtypeStructs for sharded restore.

    Mirrors exactly what ``create_sharded_state(model, optimizer, ...)``
    produces — the optimizer's ``state_template`` defines the opt-state
    structure (``optimizer=None`` defaults to AdamW's mu/nu/step layout).
    With ``mesh=None`` the leaves carry no sharding (single-process
    restore).
    """
    from shifu_tpu.train.optimizer import AdamW

    optimizer = AdamW() if optimizer is None else optimizer
    scalar = (
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        if mesh is not None
        else None
    )
    params_tmpl = shd.abstract_params(model, mesh, rules)
    opt = optimizer.state_template(
        params_tmpl, jax.ShapeDtypeStruct((), jnp.int32, sharding=scalar)
    )
    return TrainState(params=params_tmpl, opt=opt)


class Checkpointer:
    """Manage a directory of step-indexed checkpoints.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3, save_interval_steps=1000)
        ckpt.save(step, state, host_state={"batches_seen": n})   # async
        ...
        # pass the SAME optimizer used for training — the restore template's
        # opt-state structure comes from it (AdamW if omitted)
        template = abstract_train_state(model, mesh, optimizer=opt)
        state, host = ckpt.restore(template)                      # latest
        ckpt.close()
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory), options=options
        )

    # ------------------------------------------------------------------ save
    def save(
        self,
        step: int,
        state: TrainState,
        host_state: Optional[Mapping[str, Any]] = None,
        *,
        force: bool = False,
    ) -> bool:
        """Queue a checkpoint. Returns False when gated by the interval."""
        return self._mgr.save(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                host=ocp.args.JsonSave(dict(host_state or {})),
            ),
            force=force,
        )

    # --------------------------------------------------------------- restore
    def restore(self, template: TrainState, step: Optional[int] = None):
        """Restore (state, host_state) at ``step`` (default: latest).

        ``template`` is a concrete TrainState or the output of
        :func:`abstract_train_state`; leaf shardings (when present) place
        shards straight onto devices.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        out = self._mgr.restore(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                host=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["host"]

    def restore_params(self, model, step: Optional[int] = None, *,
                       mesh=None, rules=shd.DEFAULT_RULES):
        """Restore ONLY the params subtree (partial read).

        For eval/serving: reads ~1/3 of an AdamW checkpoint's bytes (no
        optimizer moments) and needs no knowledge of which optimizer
        trained it. ``model`` provides the params template via its specs.

        Call on a FRESH Checkpointer: orbax pins one restore-handler type
        per item per manager, so mixing with save()/restore() on the same
        instance raises a handler-registry error.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        template = {"params": shd.abstract_params(model, mesh, rules)}
        out = self._mgr.restore(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.PyTreeRestore(
                    item=template, partial_restore=True
                ),
            ),
        )
        return out["state"]["params"]

    # ------------------------------------------------------------- inventory
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    # -------------------------------------------------------------- lifecycle
    def wait(self):
        """Block until queued async saves are durable on disk."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
