"""The persistent autotuner: ``shifu_tpu tune``.

Times every applicable kernel variant for each benchmark leg's shape
classes ONCE and writes the winners as a versioned table artifact
(tune.table). Legs mirror the soft spots the benchgate floors watch:

  ``lcw``  windowed long-context flash attention (s=8192, w=1024 —
           the lcw_mfu 0.58 floor's configuration),
  ``g2``   the Gemma-2 stack's TWO per-layer shape classes (softcap +
           window on even layers, softcap + full causal on odd — the
           g2_mfu 0.55 floor; tuning them independently is the
           per-layer heterogeneous lever the PR-4 lax.cond dispatch
           enables),
  ``moe``  grouped-vs-einsum MoE dispatch at the bench leg's shape
           (the moe_mfu 0.45 floor).

Each candidate is timed fwd+grad (the floors are TRAINING MFU floors)
with a best-of-N wall timer. The timer is INJECTABLE — tests drive a
deterministic walk on CPU with a fake timer and never build the
workloads at all (the workload thunk is lazy).

``--preset smoke`` shrinks every leg to CPU-interpret-feasible shapes:
a real end-to-end tune (resolve -> time -> write -> load -> serve)
that finishes in seconds, for CI and for trying the flow without a
TPU. Winners from a smoke tune are keyed by the smoke shape classes
and device kind, so they can never leak into production selection.
"""

from __future__ import annotations

import dataclasses
import time
from datetime import datetime, timezone
from typing import Callable, Dict, List, Optional, Sequence

from shifu_tpu.ops.pallas import registry as reg
from shifu_tpu.tune.table import TuneTable

TUNE_LEGS = ("moe", "lcw", "g2")


@dataclasses.dataclass(frozen=True)
class TuneCase:
    """One shape class to tune: ``make_fn(variant)`` builds a zero-arg
    timed closure (jitted fwd+grad, block_until_ready inside)."""

    leg: str
    sc: reg.ShapeClass
    make_fn: Callable[[reg.KernelVariant], Callable[[], None]]


# -------------------------------------------------------------------------
# workloads
# -------------------------------------------------------------------------


def _flash_case(leg: str, *, seq: int, heads: int, kv_heads: int,
                head_dim: int, window: Optional[int],
                softcap: Optional[float], dtype) -> TuneCase:
    sc = reg.ShapeClass.flash(
        kv_len=seq, head_dim=head_dim, gqa=heads // kv_heads,
        window=window, softcap=softcap, dtype=dtype,
    )

    def make(variant: reg.KernelVariant) -> Callable[[], None]:
        import jax
        import jax.numpy as jnp

        from shifu_tpu.ops.attention import dot_product_attention
        from shifu_tpu.ops.pallas.flash_attention import flash_attention

        kq, kk, kv = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(kq, (1, seq, heads, head_dim), dtype)
        k = jax.random.normal(kk, (1, seq, kv_heads, head_dim), dtype)
        v = jax.random.normal(kv, (1, seq, kv_heads, head_dim), dtype)

        if variant.p.get("impl") == "xla":
            def attn(q, k, v):
                return dot_product_attention(
                    q, k, v, causal=True, window=window,
                    softcap=softcap, impl="xla",
                )
        else:
            def attn(q, k, v):
                return flash_attention(
                    q, k, v, window=window, softcap=softcap,
                    variant=variant,
                )

        def loss(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def run():
            jax.block_until_ready(step(q, k, v))

        return run

    return TuneCase(leg, sc, make)


def _moe_case(leg: str, *, seq: int, dim: int, experts: int, top_k: int,
              mlp_dim: int, batch: int, dtype) -> TuneCase:
    sc = reg.ShapeClass.moe(
        seq_len=seq, dim=dim, experts=experts, top_k=top_k, dtype=dtype,
    )

    def make(variant: reg.KernelVariant) -> Callable[[], None]:
        import jax
        import jax.numpy as jnp

        from shifu_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )

        cfg = TransformerConfig.tiny(
            dim=dim, mlp_dim=mlp_dim, n_experts=experts,
            moe_top_k=top_k, n_layers=1, n_heads=4, n_kv_heads=2,
            moe_impl=str(variant.p.get("impl", "grouped")),
        )
        model = Transformer(cfg)
        params = model.init(jax.random.key(0))
        blocks = {kk: vv[0] for kk, vv in params["blocks"].items()}
        x = jax.random.normal(jax.random.key(1), (batch, seq, dim), dtype)

        def loss(blocks, x):
            out, _aux = model._moe_ffn(blocks, x)
            return out.astype(jnp.float32).sum()

        step = jax.jit(jax.grad(loss))

        def run():
            jax.block_until_ready(step(blocks, x))

        return run

    return TuneCase(leg, sc, make)


def tune_cases(legs: Sequence[str] = TUNE_LEGS,
               preset: str = "full") -> List[TuneCase]:
    """The shape classes each leg tunes. ``full`` mirrors the bench
    legs (TPU-sized); ``smoke`` is CPU-interpret feasible."""
    if preset not in ("full", "smoke"):
        raise ValueError(f"preset={preset!r} (want 'full' or 'smoke')")
    import jax.numpy as jnp

    full = preset == "full"
    dt = jnp.bfloat16 if full else jnp.float32
    cases: List[TuneCase] = []
    for leg in legs:
        if leg == "lcw":
            cases.append(_flash_case(
                "lcw",
                seq=8192 if full else 512, heads=16 if full else 4,
                kv_heads=4 if full else 2,
                head_dim=128 if full else 16,
                window=1024 if full else 64, softcap=None, dtype=dt,
            ))
        elif leg == "g2":
            kw = dict(
                seq=4096 if full else 256, heads=16 if full else 4,
                kv_heads=4 if full else 2,
                head_dim=128 if full else 16,
                softcap=50.0 if full else 30.0, dtype=dt,
            )
            # The alternating stack's two per-layer classes, tuned
            # independently (per-layer heterogeneous variants).
            cases.append(_flash_case(
                "g2", window=512 if full else 64, **kw
            ))
            cases.append(_flash_case("g2", window=None, **kw))
        elif leg == "moe":
            cases.append(_moe_case(
                "moe",
                seq=2048 if full else 64, dim=1024 if full else 32,
                experts=8 if full else 4, top_k=2,
                mlp_dim=2816 if full else 32, batch=8 if full else 2,
                dtype=dt,
            ))
        else:
            raise ValueError(
                f"unknown tune leg {leg!r} (want one of {TUNE_LEGS})"
            )
    return cases


# -------------------------------------------------------------------------
# timing + the walk
# -------------------------------------------------------------------------


def make_wall_timer(repeats: int = 3,
                    warmup: int = 1) -> Callable:
    """Best-of-N wall timer: ``timer(case, variant, make_fn) -> s``.

    ``make_fn`` is a LAZY thunk returning the timed closure — an
    injected fake timer (tests) never calls it, so a deterministic
    autotune walk builds no workloads at all."""

    def timer(case: TuneCase, variant: reg.KernelVariant,
              make_fn: Callable[[], Callable[[], None]]) -> float:
        run = make_fn()
        for _ in range(max(0, warmup)):
            run()
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        return best

    return timer


def autotune(legs: Sequence[str] = TUNE_LEGS, *, preset: str = "full",
             timer: Optional[Callable] = None,
             repeats: int = 3) -> TuneTable:
    """Time every applicable variant per shape class; return the
    winner table. Ties (and anything within measurement identity)
    resolve to the EARLIER registration — v0 wins unless a challenger
    strictly beats it, so a noisy tie can never flip the default."""
    timer = timer if timer is not None else make_wall_timer(repeats)
    # Tuning must measure each candidate AS ASKED — a previously
    # activated table must not redirect the grouped-MoE or flash
    # workloads mid-measurement.
    prev = reg.active_table()
    reg.set_active_table(None)
    try:
        entries: Dict[str, dict] = {}
        for case in tune_cases(legs, preset):
            cands: Dict[str, float] = {}
            best_name, best_t = None, float("inf")
            for v in reg.variants_for(case.sc):
                t = float(timer(case, v, lambda v=v: case.make_fn(v)))
                cands[v.name] = round(t * 1000, 4)
                if t < best_t:
                    best_name, best_t = v.name, t
            if best_name is None:
                continue  # no applicable variants (cannot happen: v0)
            entries[case.sc.token] = {
                "leg": case.leg,
                "variant": best_name,
                "ms": cands[best_name],
                "candidates_ms": cands,
            }
    finally:
        reg.set_active_table(prev)
    return TuneTable(
        device_kind=reg._device_kind(),
        entries=entries,
        created=datetime.now(timezone.utc).isoformat(
            timespec="seconds"
        ),
        legs=tuple(dict.fromkeys(c for c in legs)),
    )


def check_registry(legs: Sequence[str] = TUNE_LEGS,
                   preset: str = "full") -> dict:
    """``shifu_tpu tune --check``: no timing — validate that every
    leg's shape classes resolve (v0 applies everywhere, candidate
    names unique, at least one challenger to measure). Fast enough
    for the tier-1 path."""
    problems: List[str] = []
    rows = []
    for case in tune_cases(legs, preset):
        cands = reg.variants_for(case.sc)
        names = [v.name for v in cands]
        if len(set(names)) != len(names):
            problems.append(f"{case.sc.token}: duplicate variant names")
        if not cands or cands[0].name != "v0":
            problems.append(
                f"{case.sc.token}: v0 missing or not first"
            )
        if len(cands) < 2:
            problems.append(
                f"{case.sc.token}: nothing to tune (only "
                f"{names or 'no variants'})"
            )
        rows.append({
            "leg": case.leg,
            "shape_class": case.sc.token,
            "candidates": names,
        })
    # Round-trip an empty artifact through the validating constructor:
    # a schema drift between writer and reader fails here, not in prod.
    t = TuneTable(device_kind=reg._device_kind(), entries={})
    TuneTable.from_doc(t.to_doc())
    return {
        "status": "ok" if not problems else "fail",
        "cases": rows,
        "problems": problems,
    }
