"""Persistent kernel autotuning (``shifu_tpu tune``).

Pairs with the variant registry (shifu_tpu.ops.pallas.registry): the
registry names WHAT can run per shape class; this package measures
WHICH to run on a given device and persists the winners as a
versioned, content-hashed artifact that ``--tune-table`` activates and
``shifu_tpu obs check-tune`` diffs.
"""

from shifu_tpu.tune.autotune import (
    TUNE_LEGS,
    autotune,
    check_registry,
    make_wall_timer,
    tune_cases,
)
from shifu_tpu.tune.table import (
    TuneTable,
    TuneTableError,
    check_table,
    diff_tables,
    load_table,
    save_table,
)

__all__ = [
    "TUNE_LEGS",
    "TuneTable",
    "TuneTableError",
    "autotune",
    "check_registry",
    "check_table",
    "diff_tables",
    "load_table",
    "make_wall_timer",
    "save_table",
    "tune_cases",
]
