"""The persistent winner-table artifact (``kernels.tune.json``).

``shifu_tpu tune`` benchmarks every applicable kernel variant per
shape class ONCE and persists the winners here — a versioned,
schema-checked, content-hashed JSON artifact that engine/bench/train
activate via ``--tune-table`` and the benchgate diffs via ``shifu_tpu
obs check-tune``. The table is a reviewable fact, like a BENCH row:
a winner changing between two tunes is a diff a human signs off on,
not a silent behavioral drift.

Failure posture (enforced by ops.pallas.registry.use_table and pinned
in tests/test_tune.py): a missing, corrupt (content-hash mismatch),
schema-incompatible, or wrong-device artifact NEVER breaks the caller
— it falls back to ``v0`` with a one-line warning.

Artifact shape (schema 1)::

    {
      "kind": "shifu_tpu.kernel_tune_table",
      "schema": 1,
      "device_kind": "TPU v5 lite",
      "created": "2026-08-04T12:00:00Z",
      "legs": ["moe", "lcw", "g2"],
      "entries": {
        "flash:sb8192:d128:g4:w1024:c0:bf16": {
          "variant": "wgrid_x2",
          "ms": 41.2,
          "candidates_ms": {"v0": 41.2, "full_grid": 58.0, ...}
        },
        ...
      },
      "content_hash": "sha256:..."   // over (schema, device_kind, entries)
    }
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
from typing import Dict, Optional, Tuple

SCHEMA_VERSION = 1
ARTIFACT_KIND = "shifu_tpu.kernel_tune_table"


class TuneTableError(ValueError):
    """The artifact is not a usable tune table (corrupt / wrong kind /
    incompatible schema / malformed entries)."""


def _canonical_hash(schema: int, device_kind: str,
                    entries: Dict[str, dict]) -> str:
    blob = json.dumps(
        {"schema": schema, "device_kind": device_kind,
         "entries": entries},
        sort_keys=True, separators=(",", ":"),
    ).encode()
    return "sha256:" + hashlib.sha256(blob).hexdigest()


@dataclasses.dataclass
class TuneTable:
    device_kind: str
    entries: Dict[str, dict]  # shape-class token -> {"variant", "ms", ...}
    schema: int = SCHEMA_VERSION
    created: str = ""
    legs: Tuple[str, ...] = ()

    def winner(self, token: str) -> Optional[str]:
        e = self.entries.get(token)
        return e.get("variant") if isinstance(e, dict) else None

    def content_hash(self) -> str:
        return _canonical_hash(self.schema, self.device_kind, self.entries)

    def to_doc(self) -> dict:
        return {
            "kind": ARTIFACT_KIND,
            "schema": self.schema,
            "device_kind": self.device_kind,
            "created": self.created,
            "legs": list(self.legs),
            "entries": self.entries,
            "content_hash": self.content_hash(),
        }

    @classmethod
    def from_doc(cls, doc: object) -> "TuneTable":
        """Validating constructor — every way an artifact can be wrong
        raises :class:`TuneTableError` with a one-line reason."""
        if not isinstance(doc, dict):
            raise TuneTableError("artifact is not a JSON object")
        if doc.get("kind") != ARTIFACT_KIND:
            raise TuneTableError(
                f"kind={doc.get('kind')!r} (want {ARTIFACT_KIND!r})"
            )
        schema = doc.get("schema")
        if schema != SCHEMA_VERSION:
            raise TuneTableError(
                f"schema {schema!r} incompatible with reader "
                f"{SCHEMA_VERSION}"
            )
        device_kind = doc.get("device_kind")
        if not isinstance(device_kind, str) or not device_kind:
            raise TuneTableError("missing device_kind")
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            raise TuneTableError("missing entries object")
        for token, e in entries.items():
            if not isinstance(e, dict) or not isinstance(
                e.get("variant"), str
            ):
                raise TuneTableError(
                    f"entry {token!r} lacks a variant name"
                )
        want = doc.get("content_hash")
        got = _canonical_hash(schema, device_kind, entries)
        if want != got:
            raise TuneTableError(
                "content hash mismatch (artifact corrupt or "
                "hand-edited without rehashing)"
            )
        return cls(
            device_kind=device_kind,
            entries=dict(entries),
            schema=schema,
            created=str(doc.get("created", "")),
            legs=tuple(doc.get("legs", ())),
        )


def save_table(table: TuneTable, path: str) -> None:
    """Atomic write (tmp + rename) so a crashed tune never leaves a
    torn artifact where ``--tune-table`` will find it."""
    doc = table.to_doc()
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tune.", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_table(path: str) -> TuneTable:
    """Load + validate; raises OSError / TuneTableError on anything
    short of a well-formed, hash-verified artifact."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise TuneTableError(f"not JSON: {e}") from e
    return TuneTable.from_doc(doc)


def check_table(table: TuneTable,
                device_kind: Optional[str] = None) -> list:
    """Semantic validation against the LIVE registry: every entry's
    token must parse, its winner must be a registered variant that
    applies to the class. Returns a list of problem strings (empty =
    clean). ``device_kind``: also flag a device mismatch."""
    from shifu_tpu.ops.pallas import registry as reg

    problems = []
    if device_kind is not None and table.device_kind != device_kind:
        problems.append(
            f"device_kind {table.device_kind!r} != running "
            f"{device_kind!r}"
        )
    for token, e in sorted(table.entries.items()):
        try:
            sc = reg.ShapeClass.parse(token)
        except ValueError as err:
            problems.append(str(err))
            continue
        name = e.get("variant")
        v = reg.get_variant(sc.kind, name)
        if v is None:
            problems.append(
                f"{token}: winner {name!r} is not a registered "
                f"{sc.kind} variant"
            )
        elif not v.applies(sc):
            problems.append(
                f"{token}: winner {name!r} does not apply to this "
                "shape class"
            )
        ms = e.get("ms")
        if ms is not None and not isinstance(ms, (int, float)):
            problems.append(f"{token}: ms is not a number")
    return problems


def diff_tables(old: TuneTable, new: TuneTable) -> dict:
    """A reviewable winner-table diff (``shifu_tpu obs check-tune``).

    Winners are the gated fact; per-candidate timings are recorded
    context (they wobble run to run and do not make two tables
    "different"). ``status`` is "identical" when device kind and every
    winner agree, else "changed"."""
    changed = []
    for token in sorted(set(old.entries) & set(new.entries)):
        o, n = old.winner(token), new.winner(token)
        if o != n:
            changed.append({
                "shape_class": token,
                "old": o,
                "new": n,
                "old_ms": old.entries[token].get("ms"),
                "new_ms": new.entries[token].get("ms"),
            })
    added = sorted(set(new.entries) - set(old.entries))
    removed = sorted(set(old.entries) - set(new.entries))
    identical = (
        not changed and not added and not removed
        and old.device_kind == new.device_kind
    )
    return {
        "status": "identical" if identical else "changed",
        "device_kind": {"old": old.device_kind, "new": new.device_kind},
        "schema": {"old": old.schema, "new": new.schema},
        "content_hash": {
            "old": old.content_hash(), "new": new.content_hash(),
        },
        "changed": changed,
        "added": [
            {"shape_class": t, "variant": new.winner(t)} for t in added
        ],
        "removed": [
            {"shape_class": t, "variant": old.winner(t)} for t in removed
        ],
    }
