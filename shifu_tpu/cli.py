"""Command-line entry points: ``python -m shifu_tpu <cmd>``.

    train      run the Trainer loop (real corpus dir or --synthetic)
    dpo        DPO preference tuning from a JSONL of pairs
    eval       perplexity over a dataset (params-only checkpoint read)
    generate   text completion from a checkpoint
    serve      HTTP completions server (continuous batching, paged KV);
               with --fleet host:port,... it becomes the FLEET ROUTER
               federating remote serve hosts (shifu_tpu/fleet)
    fleet      fleet administration: `rollout` = zero-downtime rolling
               weight rollout across a live router (drain -> /reloadz
               hot-swap -> readiness gate -> resume, SLO-braked);
               `snapshot` = training ckpt -> checksum-manifest params
               dir (the artifact rollout verifies)
    bpe-train  train a byte-level BPE tokenizer (native C++ core)
    trace      export serving request traces as Chrome trace-event JSON
    debug      dump the flight-recorder ring (live server's /debugz or
               the in-process ring)
    tune       persistent kernel autotuner: time every registered
               kernel variant per shape class (legs moe/lcw/g2) and
               write the winner table as a versioned artifact that
               serve/train/bench activate via --tune-table; --check
               validates registry + artifact schema without timing
    loadgen    measurement harness: replay a declarative scenario mix
               at a fixed open-loop offered load against a live
               router/server and exit with per-tier SLO verdicts
               scored from the real /sloz + federated /metrics scrape
               (exit 1 when a tier burns its budget); the scenario's
               chaos track folds SIGKILL/drain/resume/mid-run rollout
               into the timeline; --check validates a scenario with
               no traffic
    obs        check-bench: gate a compact bench line against a
               recorded baseline (exit 1 on regression);
               check-tune: diff two tune-table artifacts (exit 1 when
               winners changed — a reviewable, gated fact)
    info       devices, native-extension status, version

The CLI builds everything from flags — model preset (optionally MoE),
optimizer + schedule, mesh plan — and is the reference example of wiring
the framework end to end. ``generate``/``serve`` default to the byte
tokenizer; pass ``--tokenizer bpe.json`` (a bpe-train artifact) to use
a trained vocabulary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _size_bytes(text: str) -> int:
    """Parse a byte-size flag value: plain int, or k/m/g/t-suffixed
    (binary units: "4g" = 4 GiB)."""
    s = str(text).strip().lower()
    mult = 1
    if s and s[-1] in "kmgt":
        mult = 1 << (10 * ("kmgt".index(s[-1]) + 1))
        s = s[:-1]
    try:
        return int(float(s) * mult)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"not a byte size: {text!r} (want e.g. 1073741824, 512m, 4g)"
        ) from None


def _build_mesh(spec: str):
    """'fsdp=2,tp=2' -> built Mesh (axes validated by MeshPlan)."""
    from shifu_tpu.parallel import MeshPlan

    kw = {}
    for part in spec.split(","):
        name, _, val = part.partition("=")
        kw[name.strip()] = int(val)
    return MeshPlan(**kw).build()


def _build_optimizer(args, total_steps: int):
    from shifu_tpu import train as T

    sched = {
        "constant": lambda: T.constant(args.lr),
        "cosine": lambda: T.warmup_cosine(
            args.lr, total_steps, warmup_steps=args.warmup
        ),
        "linear": lambda: T.linear(args.lr, total_steps, warmup_steps=args.warmup),
        "wsd": lambda: T.wsd(args.lr, total_steps, warmup_steps=args.warmup),
        "inverse_sqrt": lambda: T.inverse_sqrt(args.lr, max(1, args.warmup)),
    }[args.schedule]()
    return {
        "adamw": lambda: T.AdamW(schedule=sched),
        "lion": lambda: T.Lion(schedule=sched),
        "adafactor": lambda: T.Adafactor(schedule=sched),
        "sgd": lambda: T.SGD(schedule=sched),
    }[args.optimizer]()


def _build_model(args):
    import dataclasses

    from shifu_tpu.models import Mamba, MambaConfig, Transformer, TransformerConfig

    tune_table = getattr(args, "tune_table", None)
    if tune_table:
        # Activate eagerly so a junk artifact warns at STARTUP (and
        # /statz's kernels block reflects it), not at first trace.
        from shifu_tpu.ops.pallas import registry as _preg

        _preg.use_table(tune_table)
    if args.family == "mamba":
        if args.moe_experts or args.attn:
            raise SystemExit(
                "--moe-experts/--attn are transformer-family flags"
            )
        cfg = {"tiny": MambaConfig.tiny, "small": MambaConfig.small}.get(
            args.preset
        )
        if cfg is None:
            raise SystemExit(f"no mamba preset {args.preset!r}")
        return Mamba(cfg())
    cfg = {
        "tiny": TransformerConfig.tiny,
        "small": TransformerConfig.small,
        "1b": TransformerConfig.base_1b,
        "7b": TransformerConfig.large_7b,
    }[args.preset]()
    if args.moe_experts:
        cfg = dataclasses.replace(cfg, n_experts=args.moe_experts)
    if args.attn:
        cfg = dataclasses.replace(cfg, attn_impl=args.attn)
    if tune_table:
        cfg = dataclasses.replace(cfg, tune_table=tune_table)
    return Transformer(cfg)


def cmd_train(args) -> int:
    import jax

    from shifu_tpu.train.loop import Trainer, TrainLoopConfig

    model = _build_model(args)
    optimizer = _build_optimizer(args, args.steps)
    mesh = _build_mesh(args.mesh) if args.mesh else None

    if args.data and args.synthetic:
        print("--data and --synthetic are mutually exclusive", file=sys.stderr)
        return 2
    if args.data:
        from shifu_tpu.data import PackedLoader, TokenDataset

        loader = PackedLoader(
            TokenDataset(args.data),
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            seed=args.seed,
            microbatches=args.microbatches,
        )
    else:
        from shifu_tpu.data.synthetic import SyntheticLoader

        loader = SyntheticLoader(
            vocab_size=model.cfg.vocab_size,
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            seed=args.seed,
            microbatches=args.microbatches,
        )

    cfg = TrainLoopConfig(
        total_steps=args.steps,
        log_every=args.log_every,
        ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every,
        metrics_path=args.metrics,
        microbatches=args.microbatches,
    )
    trainer = Trainer(
        model,
        optimizer,
        loader,
        cfg,
        mesh=mesh,
        rng=jax.random.key(args.seed),
    )
    state = trainer.run()
    print(f"done: step={int(state.step)}")
    return 0


def _build_tokenizer(args):
    """The byte tokenizer, or a trained BPE table (--tokenizer)."""
    if getattr(args, "tokenizer", None):
        from shifu_tpu.data.bpe import BPETokenizer

        return BPETokenizer.load(args.tokenizer)
    from shifu_tpu.data.tokenizer import ByteTokenizer

    return ByteTokenizer()


def cmd_bpe_train(args) -> int:
    from shifu_tpu.data.bpe import BPETokenizer, native_bpe_available

    texts = []
    for path in args.data:
        with open(path, encoding="utf-8") as f:
            if args.per_line:
                texts.extend(line.rstrip("\n") for line in f)
            else:
                texts.append(f.read())
    if not texts:
        print("no input text", file=sys.stderr)
        return 2
    tok = BPETokenizer.train(texts, vocab_size=args.vocab_size)
    tok.save(args.out)
    print(json.dumps({
        "out": args.out,
        "vocab_size": tok.vocab_size,
        "merges": len(tok.merges),
        "native_core": native_bpe_available(),
        "docs": len(texts),
    }))
    return 0


def _save_state(out_dir: str, step: int, state) -> None:
    """Save a tuned TrainState to ``out_dir`` (the shared tail of the
    dpo/grpo/distill commands — one place for save semantics)."""
    from shifu_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(out_dir)
    try:
        ckpt.save(step, state, force=True)
        ckpt.wait()
    finally:
        ckpt.close()


def cmd_dpo(args) -> int:
    """DPO from a JSONL of {"prompt", "chosen", "rejected"} — token-id
    lists, or strings when a tokenizer is given. The restored
    checkpoint is BOTH the starting policy and the frozen reference
    (the standard recipe: tune away from the SFT model)."""
    import jax

    from shifu_tpu.data.preference import iter_pair_batches
    from shifu_tpu.train import (
        DPOConfig,
        DPOModel,
        TrainState,
        make_train_step,
        reference_logprobs,
    )

    model = _build_model(args)
    tok = _build_tokenizer(args) if args.tokenizer else None
    pairs = []
    with open(args.data, encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            row = []
            for key in ("prompt", "chosen", "rejected"):
                v = obj[key]
                if isinstance(v, str):
                    if tok is None:
                        print(
                            f"string {key!r} needs --tokenizer",
                            file=sys.stderr,
                        )
                        return 2
                    v = tok.encode(v)
                row.append([int(t) for t in v])
            pairs.append(tuple(row))
    if not pairs:
        print("no pairs in --data", file=sys.stderr)
        return 2

    import contextlib
    import itertools

    import jax.numpy as jnp

    if tok is not None and tok.vocab_size > model.cfg.vocab_size:
        print(
            f"warning: tokenizer vocab {tok.vocab_size} exceeds model "
            f"vocab {model.cfg.vocab_size}; ids are clipped",
            file=sys.stderr,
        )
        pairs = [
            tuple(
                [min(t, model.cfg.vocab_size - 1) for t in seq]
                for seq in row
            )
            for row in pairs
        ]
    params = _restore_params(args, model)
    ref_params = params  # frozen; the step never donates it (see below)
    dm = DPOModel(model, DPOConfig(beta=args.beta, loss_type=args.loss_type))
    optimizer = _build_optimizer(args, args.steps)
    mesh = _build_mesh(args.mesh) if args.mesh else None
    with contextlib.ExitStack() as ctx:
        if mesh is not None:
            ctx.enter_context(mesh)
        if mesh is None:
            # The train step DONATES its state; start it from a copy so
            # ref_params stays alive for reference_logprobs all run.
            state = TrainState.create(
                jax.tree_util.tree_map(lambda x: x.copy(), params),
                optimizer,
            )
        else:
            # The standard mesh recipe (Trainer does the same): state
            # created directly into its shards, batches sharded per
            # step — a host-resident state would fight the step's
            # in_shardings.
            from shifu_tpu.train import state_shardings

            st_shard = state_shardings(dm, mesh, optimizer=optimizer)
            state = jax.jit(
                lambda p: TrainState.create(p, optimizer),
                out_shardings=st_shard,
            )(params)
        step = make_train_step(dm, optimizer, mesh)
        eos = tok.eos_id if tok is not None else None
        raw_batches = list(iter_pair_batches(
            pairs, args.batch_size, args.seq_len, eos_id=eos,
            seed=args.seed,
        ))
        if not raw_batches:
            print(
                f"{len(pairs)} pairs cannot fill one batch of "
                f"{args.batch_size}; lower --batch-size",
                file=sys.stderr,
            )
            return 2
        # Score the frozen reference ONCE per distinct batch (jitted,
        # params as an argument — a closure would embed them as program
        # constants), then cycle the augmented batches.
        ref_fn = jax.jit(
            lambda p, b: reference_logprobs(model, p, b)
        )

        def prep(raw):
            b = {k: jnp.asarray(v) for k, v in raw.items()}
            if mesh is not None:
                from shifu_tpu.parallel import shard_batch

                b = shard_batch(b, mesh)
            return ref_fn(ref_params, b)

        batches = itertools.cycle([prep(r) for r in raw_batches])

        for i in range(args.steps):
            state, m = step(state, next(batches))
            if args.log_every and (i % args.log_every == 0):
                print(json.dumps({
                    "step": i,
                    "loss": round(float(m["loss"]), 5),
                    "reward_margin": round(float(m["reward_margin"]), 5),
                    "accuracy": round(float(m["accuracy"]), 4),
                }), flush=True)
    if args.out_ckpt_dir:
        _save_state(args.out_ckpt_dir, args.steps, state)
    print(json.dumps({"done": args.steps, "pairs": len(pairs)}))
    return 0


def cmd_distill(args) -> int:
    """Knowledge distillation from a larger teacher checkpoint: the
    teacher annotates each batch with its top-k next-token
    log-probabilities (a separate jitted inference forward), the
    student trains on alpha*CE + (1-alpha)*T^2*KL through the ordinary
    sharded train stack (train/distill.py)."""
    import contextlib
    import itertools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from shifu_tpu.train import (
        DistillConfig,
        DistillModel,
        TrainState,
        make_teacher_annotate_fn,
        make_train_step,
    )

    model = _build_model(args)
    targs = argparse.Namespace(**vars(args))
    targs.preset = args.teacher_preset
    targs.ckpt_dir = args.teacher_ckpt_dir
    # Student-architecture flags must NOT leak into the teacher build —
    # an --moe-experts student from a dense teacher checkpoint would
    # otherwise construct an MoE teacher that cannot restore it. A
    # DIFFERENT seed keeps the no-checkpoint random-teacher mode
    # meaningful (same preset + same seed would clone the student:
    # kd_kl identically zero).
    targs.moe_experts = 0
    targs.seed = args.seed + 1
    teacher = _build_model(targs)
    if teacher.cfg.vocab_size != model.cfg.vocab_size:
        print(
            f"teacher vocab {teacher.cfg.vocab_size} != student vocab "
            f"{model.cfg.vocab_size}: kd indices would be silently "
            "clamped — distillation needs a shared vocabulary",
            file=sys.stderr,
        )
        return 2
    tok = _build_tokenizer(args) if args.tokenizer else None
    if tok is not None and tok.vocab_size > model.cfg.vocab_size:
        print(
            f"warning: tokenizer vocab {tok.vocab_size} exceeds model "
            f"vocab {model.cfg.vocab_size}; ids are clipped",
            file=sys.stderr,
        )

    rows = []
    with open(args.data, encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            v = obj.get("tokens", obj.get("text"))
            if isinstance(v, str):
                if tok is None:
                    print("string 'text' needs --tokenizer",
                          file=sys.stderr)
                    return 2
                v = tok.encode(v)
            if v:
                rows.append([
                    min(int(t), model.cfg.vocab_size - 1) for t in v
                ])
    if not rows:
        print("no rows in --data", file=sys.stderr)
        return 2

    s = args.seq_len
    packed, masks = [], []
    for r in rows:
        r = r[:s]
        m = [1.0] * len(r) + [0.0] * (s - len(r))
        packed.append(r + [0] * (s - len(r)))
        masks.append(m)
    nb = len(packed) // args.batch_size
    if not nb:
        print(
            f"{len(packed)} rows cannot fill one batch of "
            f"{args.batch_size}; lower --batch-size",
            file=sys.stderr,
        )
        return 2

    params = _restore_params(args, model)
    teacher_params = _restore_params(targs, teacher)
    dcfg = DistillConfig(
        alpha=args.alpha, temperature=args.kd_temperature,
        top_k=args.kd_top_k,
    )
    dm = DistillModel(model, dcfg)
    optimizer = _build_optimizer(args, args.steps)
    mesh = _build_mesh(args.mesh) if args.mesh else None
    annotate = make_teacher_annotate_fn(teacher, dcfg)
    with contextlib.ExitStack() as ctx:
        if mesh is not None:
            from shifu_tpu.parallel import shard_params
            from shifu_tpu.train import state_shardings

            ctx.enter_context(mesh)
            teacher_params = shard_params(teacher, teacher_params, mesh)
            st_shard = state_shardings(dm, mesh, optimizer=optimizer)
            state = jax.jit(
                lambda p: TrainState.create(p, optimizer),
                out_shardings=st_shard,
            )(shard_params(model, params, mesh))
        else:
            state = TrainState.create(
                jax.tree_util.tree_map(lambda x: x.copy(), params),
                optimizer,
            )
        step = make_train_step(dm, optimizer, mesh)

        def prep(i):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            b = {
                "tokens": jnp.asarray(np.asarray(packed[sl], np.int32)),
                "mask": jnp.asarray(np.asarray(masks[sl], np.float32)),
            }
            if mesh is not None:
                from shifu_tpu.parallel import shard_batch

                b = shard_batch(b, mesh)
            return annotate(teacher_params, b)

        # Annotate LAZILY: eagerly prepping the whole dataset would run
        # a teacher forward per batch and hold every (b, s, k)
        # annotation on device before step 0 — at a corpus scale where
        # only --steps batches are ever consumed, that is unbounded
        # wasted teacher compute + HBM. A small memo keeps the common
        # cycle-a-tiny-dataset case to one annotation per batch.
        memo: dict = {}
        idxs = itertools.cycle(range(nb))

        def next_batch():
            i = next(idxs)
            if i in memo:
                return memo[i]
            b = prep(i)
            if len(memo) < 64:
                memo[i] = b
            return b

        for i in range(args.steps):
            state, m = step(state, next_batch())
            if args.log_every and (i % args.log_every == 0):
                print(json.dumps({
                    "step": i,
                    "loss": round(float(m["loss"]), 5),
                    "ce": round(float(m["ce"]), 5),
                    "kd_kl": round(float(m["kd_kl"]), 5),
                }), flush=True)
    if args.out_ckpt_dir:
        _save_state(args.out_ckpt_dir, args.steps, state)
    print(json.dumps({"done": args.steps, "rows": len(rows)}))
    return 0


def cmd_grpo(args) -> int:
    """Online RL (GRPO) with a verifiable reward: sample a group per
    prompt through the serving engine, score completions by whether
    their decoded text contains the example's "target" string, take a
    group-normalised policy-gradient step. The restored checkpoint is
    both the starting policy and (when --beta > 0) the frozen KL
    reference."""
    import contextlib
    import itertools

    import jax
    import jax.numpy as jnp

    from shifu_tpu.infer import Engine, SampleConfig
    from shifu_tpu.train import (
        GRPOConfig,
        GRPOModel,
        TrainState,
        grpo_rollout,
        make_train_step,
        reference_token_logprobs,
    )

    model = _build_model(args)
    tok = _build_tokenizer(args)
    rows = []
    with open(args.data, encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            p = obj["prompt"]
            ids = tok.encode(p) if isinstance(p, str) else [int(t) for t in p]
            ids = [min(t, model.cfg.vocab_size - 1) for t in ids]
            rows.append((ids, str(obj["target"])))
    if not rows:
        print("no examples in --data", file=sys.stderr)
        return 2
    if args.temperature <= 0.0:
        print(
            "--temperature must be > 0: greedy rollouts make every "
            "group member identical, so every advantage is 0",
            file=sys.stderr,
        )
        return 2

    params = _restore_params(args, model)
    ref_params = params  # frozen; enters the step as batch data only
    cfg = GRPOConfig(
        group_size=args.group_size, beta=args.beta,
        clip_eps=args.clip_eps,
    )
    gm = GRPOModel(model, cfg)
    optimizer = _build_optimizer(args, args.steps)
    mesh = _build_mesh(args.mesh) if args.mesh else None

    # Rewards key off the prompt's token sequence; two examples with
    # the same tokens but DIFFERENT targets would silently score every
    # earlier duplicate against the last-seen answer — refuse loudly.
    targets = {}
    for ids, t in rows:
        key = tuple(ids)
        if key in targets and targets[key] != t:
            print(
                f"duplicate prompt with conflicting targets "
                f"({targets[key]!r} vs {t!r}): rewards are keyed by "
                "prompt tokens — dedupe the data or merge the targets",
                file=sys.stderr,
            )
            return 2
        targets[key] = t

    def reward(prompt_ids, gen_ids):
        want = targets[tuple(prompt_ids)]
        return float(want in tok.decode(gen_ids))

    engine_kw = dict(
        max_slots=args.max_slots,
        max_len=args.seq_len,
        sample_cfg=SampleConfig(temperature=args.temperature),
        prefill_buckets=tuple(
            b for b in (64, 128, 256, 512, 1024, 2048) if b < args.seq_len
        ) + (args.seq_len,),
        rng=jax.random.key(args.seed),
    )
    if args.seq_len % 64 == 0:
        # Paged + prefix-cached rollouts: a group of G completions
        # shares ONE prompt prefill (the page-aligned prompt prefix is
        # registered by the first member and hit by the other G-1), and
        # successive rounds re-hit it until the params swap flushes.
        from shifu_tpu.infer.engine import PagedEngine

        engine = PagedEngine(
            model, params, page_size=64, enable_prefix_cache=True,
            **engine_kw,
        )
    else:
        # Page-unaligned seq_len (e.g. the 513 of packed-LM configs):
        # the dense engine has no alignment constraint.
        engine = Engine(model, params, **engine_kw)
    prompt_cycle = itertools.cycle([ids for ids, _ in rows])

    with contextlib.ExitStack() as ctx:
        if mesh is not None:
            ctx.enter_context(mesh)
            from shifu_tpu.train import state_shardings

            st_shard = state_shardings(gm, mesh, optimizer=optimizer)
            state = jax.jit(
                lambda p: TrainState.create(p, optimizer),
                out_shardings=st_shard,
            )(params)
        else:
            state = TrainState.create(
                jax.tree_util.tree_map(lambda x: x.copy(), params),
                optimizer,
            )
        step = make_train_step(gm, optimizer, mesh)
        ref_fn = jax.jit(
            lambda p, b: reference_token_logprobs(model, p, b)
        )
        rollout_dev = jax.devices()[0]
        for i in range(args.steps):
            # Keep the rollout params ON DEVICE: handing the engine
            # host numpy would re-upload the whole tree on every
            # prefill/decode dispatch of the round. Single-device
            # training shares the train buffers directly (the step's
            # donation only invalidates the PREVIOUS state, and this
            # rebinds from the fresh state each round); a mesh state
            # is gathered and placed once per round.
            if mesh is None:
                engine.params = state.params
            else:
                engine.params = jax.device_put(
                    jax.device_get(state.params), rollout_dev
                )
            # Cached prefix K/V was computed under the PREVIOUS round's
            # params — matching it now would mix policies silently.
            if hasattr(engine, "flush_prefix_cache"):
                engine.flush_prefix_cache()
            prompts = [
                next(prompt_cycle) for _ in range(args.prompts_per_step)
            ]
            batch, stats = grpo_rollout(
                engine, prompts, reward, cfg,
                max_new_tokens=args.max_new_tokens,
                seq_len=args.seq_len,
            )
            b = {k: jnp.asarray(v) for k, v in batch.items()}
            if mesh is not None:
                from shifu_tpu.parallel import shard_batch

                b = shard_batch(b, mesh)
            if cfg.beta > 0.0:
                b = ref_fn(ref_params, b)
            state, m = step(state, b)
            if args.log_every and (i % args.log_every == 0):
                print(json.dumps({
                    "step": i,
                    "loss": round(float(m["loss"]), 5),
                    "reward_mean": round(stats["reward_mean"], 4),
                    "kl": round(float(m["kl"]), 6),
                }), flush=True)
    if args.out_ckpt_dir:
        _save_state(args.out_ckpt_dir, args.steps, state)
    print(json.dumps({"done": args.steps, "examples": len(rows)}))
    return 0


def _restore_params(args, model):
    """Latest checkpoint's params (params-only partial read — works for
    any training optimizer); fresh init when no --ckpt-dir is given."""
    import jax

    if not args.ckpt_dir:
        return model.init(jax.random.key(args.seed))
    from shifu_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(args.ckpt_dir)
    try:
        return ckpt.restore_params(model)
    finally:
        ckpt.close()


def cmd_eval(args) -> int:
    model = _build_model(args)
    if not args.ckpt_dir:
        print(
            "warning: no --ckpt-dir; evaluating RANDOMLY INITIALIZED "
            "weights (smoke-test mode)",
            file=sys.stderr,
        )
    params = _restore_params(args, model)

    if args.task == "ppl":
        from shifu_tpu.data import PackedLoader, TokenDataset
        from shifu_tpu.train.loop import evaluate

        loader = PackedLoader(
            TokenDataset(args.data),
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            shuffle=False,
        )
        out = evaluate(model, params, loader, max_batches=args.batches)
        print(json.dumps(out))
        return 0

    tok = _build_tokenizer(args)
    rows = []
    with open(args.data) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        print(f"no examples in {args.data}", file=sys.stderr)
        return 2

    if args.task == "mc":
        # JSONL rows: {"context": str, "options": [str], "answer": int}
        from shifu_tpu.eval import encode_mc_example, evaluate_multiple_choice

        examples = [
            encode_mc_example(
                tok, r["context"], r["options"], int(r["answer"])
            )
            for r in rows
        ]
        out = evaluate_multiple_choice(
            model, params, examples,
            seq_len=args.seq_len, batch_rows=args.batch_size,
        )
        print(json.dumps(out))
        return 0

    # gen: JSONL rows {"prompt": str, "answers": [str]} (or "answer").
    from shifu_tpu.eval import encode_gen_example, evaluate_generative
    from shifu_tpu.infer import Engine, SampleConfig

    examples = [
        encode_gen_example(
            tok, r["prompt"],
            r["answers"] if "answers" in r else [r["answer"]],
        )
        for r in rows
    ]
    engine = Engine(
        model, params,
        max_slots=args.max_slots,
        max_len=args.seq_len,
        sample_cfg=SampleConfig(temperature=0.0),
        eos_id=tok.eos_id,
        prefill_buckets=tuple(
            b for b in (64, 128, 256, 512, 1024, 2048) if b < args.seq_len
        ) + (args.seq_len,),
    )
    out = evaluate_generative(
        engine, tok, examples, max_new_tokens=args.max_new_tokens,
    )
    if not args.predictions:
        del out["predictions"]
    print(json.dumps(out))
    return 0


def cmd_generate(args) -> int:
    import jax
    import jax.numpy as jnp

    from shifu_tpu.infer import SampleConfig, make_generate_fn

    model = _build_model(args)
    params = _restore_params(args, model)
    tok = _build_tokenizer(args)
    if tok.vocab_size > model.cfg.vocab_size:
        print(
            f"warning: tokenizer vocab {tok.vocab_size} exceeds model "
            f"vocab {model.cfg.vocab_size}; ids are clipped",
            file=sys.stderr,
        )
    ids = [min(i, model.cfg.vocab_size - 1) for i in tok.encode(args.prompt)]
    if not ids:
        print("--prompt must be non-empty", file=sys.stderr)
        return 2
    prompts = jnp.asarray([ids], jnp.int32)
    fn = make_generate_fn(
        model,
        max_new_tokens=args.max_new_tokens,
        sample_cfg=SampleConfig(
            temperature=args.temperature, top_p=args.top_p
        ),
        eos_id=tok.eos_id,
    )
    out = fn(
        params,
        prompts,
        jnp.asarray([len(ids)], jnp.int32),
        jax.random.key(args.seed),
    )
    text = tok.decode([int(t) for t in out["tokens"][0]])
    print(json.dumps({"prompt": args.prompt, "completion": text}))
    return 0


def build_serve_engine(args, model, params, tok):
    """Flags -> constructed serving engine — the single seam between
    the CLI surface and the engine classes (unit-tested directly; a
    feature cmd_serve cannot construct is a feature the binary does
    not ship). Raises ValueError on incoherent flag combinations.

    ``--mesh dp=D,tp=T,ep=E`` (serving axes only): T×E-device
    sub-meshes (tp shards heads/mlp/vocab, ep shards MoE EXPERT
    weights/buffers instead of replicating them — MoE decode memory
    scales with the mesh), D model REPLICAS behind one router
    (ReplicatedEngine) — D x T x E devices total. dp=1 serves one mesh
    engine; no flag serves single-device. ``ep>1`` requires an MoE
    model (a dense model has no experts axis to shard)."""
    from shifu_tpu.infer import (
        Engine,
        PagedEngine,
        PromptLookupPagedEngine,
        SampleConfig,
        SpeculativePagedEngine,
    )

    mesh_spec = getattr(args, "mesh", None)
    dp = tp = ep = 1
    if mesh_spec:
        parts = {}
        for part in mesh_spec.split(","):
            name, _, val = part.partition("=")
            parts[name.strip()] = int(val)
        unknown = set(parts) - {"dp", "tp", "ep"}
        if unknown:
            raise ValueError(
                f"serving mesh axes are dp/tp/ep, got {sorted(unknown)} "
                "(training meshes take the full MeshPlan axes)"
            )
        dp, tp = parts.get("dp", 1), parts.get("tp", 1)
        ep = parts.get("ep", 1)
        if dp < 1 or tp < 1 or ep < 1:
            raise ValueError("serving mesh sizes must be >= 1")
        if ep > 1 and not getattr(model.cfg, "n_experts", 0):
            raise ValueError(
                "--mesh ep= shards MoE expert weights; this model has "
                "no experts (n_experts=0) — use tp/dp"
            )
        if ep > 1 and getattr(model.cfg, "n_experts", 0) % ep:
            raise ValueError(
                f"ep={ep} does not divide n_experts="
                f"{model.cfg.n_experts}; expert weights would be "
                "replicated silently"
            )

    kw = dict(
        max_slots=args.max_slots,
        max_len=args.max_len,
        sample_cfg=SampleConfig(
            temperature=args.temperature, top_p=args.top_p
        ),
        # Same default stop condition as cmd_generate (the CLI is wired
        # to the byte tokenizer); --eos-id overrides for checkpoints
        # trained with another vocab, --eos-id -1 disables.
        eos_id=(
            None
            if args.eos_id == -1
            else (tok.eos_id if args.eos_id is None else args.eos_id)
        ),
        decode_chunk=args.decode_chunk,
        # Penalties and logit_bias are per-REQUEST features; without the
        # per-slot traced sampler their strengths could not vary by
        # request, so these flags imply it.
        per_request_sampling=(
            args.per_request_sampling or args.penalties or args.logit_bias
        ),
        enable_penalties=args.penalties,
        enable_logit_bias=args.logit_bias,
        # The engine's own tokenizer: string stop sequences and regex
        # constraints decode/lift tokens inside the engine loop.
        tokenizer=tok,
    )
    lora_cfg = None
    lora_dirs = getattr(args, "lora_ckpt_dir", None) or []
    if lora_dirs:
        from shifu_tpu.infer import LoraServingConfig

        lora_cfg = LoraServingConfig(
            rank=args.lora_rank,
            alpha=args.lora_alpha,
            targets=tuple(
                t.strip() for t in args.lora_targets.split(",") if t.strip()
            ),
            max_adapters=len(lora_dirs),
        )
        kw["lora"] = lora_cfg

    def load_adapters(engine):
        """Register each --lora-ckpt-dir (ids 1..n, in flag order)."""
        if not lora_dirs:
            return engine
        from shifu_tpu.checkpoint import Checkpointer
        from shifu_tpu.train import LoraConfig, LoraModel

        lm = LoraModel(
            model, params,
            LoraConfig(
                rank=lora_cfg.rank, alpha=lora_cfg.alpha,
                targets=lora_cfg.targets,
            ),
        )
        for d in lora_dirs:
            ckpt = Checkpointer(d)
            try:
                engine.add_adapter(ckpt.restore_params(lm))
            finally:
                ckpt.close()
        return engine

    draft = draft_params = None
    if args.spec != "off":
        # Round 5: the whole serving feature set COMPOSES with the
        # speculative engines — logit_bias/constraints (masked verify
        # distribution), multi-LoRA (adapter args through the verify
        # forward), and penalties (position-wise prospective counts
        # along the proposal prefix).
        kw.pop("decode_chunk")  # spec rounds replace the chunk scan
        if args.spec == "draft":
            if lora_dirs:
                raise ValueError(
                    "--lora-ckpt-dir does not compose with --spec "
                    "draft (adapters apply to the target; the draft "
                    "would propose from mismatched weights — use "
                    "--spec prompt-lookup for adapter traffic)"
                )
            if not args.draft_preset:
                raise ValueError(
                    "--spec draft needs --draft-preset (and usually "
                    "--draft-ckpt-dir with trained weights — an "
                    "untrained draft accepts ~nothing)"
                )
            import argparse as _argparse

            dargs = _argparse.Namespace(**vars(args))
            dargs.preset = args.draft_preset
            dargs.ckpt_dir = args.draft_ckpt_dir
            dargs.moe_experts = 0
            draft = _build_model(dargs)
            draft_params = _restore_params(dargs, draft)

    # --kv: KV-cache quantization for the paged pool. int8 halves KV
    # bytes (capacity/long-context lever) at a measured decode-latency
    # cost; int8-b16s narrows the scale leaves to bfloat16, recovering
    # most of that cost (~0.2% extra relative error, error-bound
    # tested). See the decision table in docs/observability.md.
    kv = getattr(args, "kv", "bf16") or "bf16"
    kv_kw = {}
    if kv != "bf16":
        if not (args.paged or args.spec != "off"):
            raise ValueError(
                "--kv int8/int8-b16s needs --paged (or a --spec "
                "engine): the int8 KV path is a paged-pool feature"
            )
        import jax.numpy as _jnp

        kv_kw["cache_dtype"] = _jnp.int8
        if kv == "int8-b16s":
            kv_kw["kv_scale_dtype"] = _jnp.bfloat16
    # Host-RAM KV tier (docs/kv_tiering.md): spilled prefix pages live
    # in host memory under --kv-host-bytes and restore asynchronously
    # on a later hit when the measured breakeven says they should.
    if getattr(args, "kv_tier", "off") == "host":
        if not getattr(args, "prefix_cache", False) or not (
            args.paged or args.spec != "off"
        ):
            raise ValueError(
                "--kv-tier host needs --prefix-cache and --paged (or "
                "a --spec engine): the host tier is keyed by "
                "prefix-chain digests over the paged pool"
            )
        kv_slots = getattr(args, "kv_export_slots", 64)
        if kv_slots < 1:
            raise ValueError(
                f"--kv-export-slots must be >= 1, got {kv_slots}"
            )
        kv_kw["kv_host_bytes"] = args.kv_host_bytes
        kv_kw["kv_export_slots"] = kv_slots
        # Disk tier below the host tier (--kv-disk-bytes/--kv-disk-dir):
        # validated HERE so a bad path refuses at startup with a fix
        # hint, not as a DiskKVStore ValueError mid-construction.
        kv_disk = getattr(args, "kv_disk_bytes", 0) or 0
        disk_dir = getattr(args, "kv_disk_dir", None)
        if kv_disk:
            if not disk_dir:
                raise ValueError(
                    "--kv-disk-bytes needs --kv-disk-dir: the disk "
                    "tier persists SKVP segment files there; fix: add "
                    "--kv-disk-dir /path/to/kv"
                )
            if not os.path.isdir(disk_dir):
                raise ValueError(
                    f"--kv-disk-dir {disk_dir} does not exist (the "
                    "tier reuses surviving segments, so it never "
                    f"mkdirs an operator path); fix: mkdir -p {disk_dir}"
                )
            if not os.access(disk_dir, os.W_OK):
                raise ValueError(
                    f"--kv-disk-dir {disk_dir} is not writable by "
                    "this process; fix: chmod/chown the directory"
                )
            kv_kw["kv_disk_bytes"] = kv_disk
            kv_kw["kv_disk_dir"] = disk_dir
        elif disk_dir:
            raise ValueError(
                "--kv-disk-dir without --kv-disk-bytes does nothing; "
                "fix: add --kv-disk-bytes 16g (or drop the dir)"
            )
    elif getattr(args, "kv_export_slots", 64) != 64:
        raise ValueError(
            "--kv-export-slots sizes the /kv/pages export table, which "
            "only exists with --kv-tier host"
        )
    elif getattr(args, "kv_disk_bytes", 0) or getattr(
        args, "kv_disk_dir", None
    ):
        raise ValueError(
            "--kv-disk-bytes/--kv-disk-dir add a disk tier BELOW the "
            "host tier; fix: add --kv-tier host (with --paged "
            "--prefix-cache)"
        )

    # Disaggregation roles (serve --role, docs/architecture.md). A
    # prefill host spills each exported request's KV chain into the
    # host tier for pickup over GET /kv/pages; a decode host ingests
    # through the same tier. Refuse a role the engine cannot honour AT
    # STARTUP — not as a failed handoff on the first real request.
    role = getattr(args, "role", "both") or "both"
    if role in ("prefill", "decode") and "kv_host_bytes" not in kv_kw:
        raise ValueError(
            f"--role {role} migrates KV pages through the host tier, "
            "which this engine is not running; fix: add --paged "
            "--prefix-cache --kv-tier host"
        )
    if role != "both" and dp > 1:
        raise ValueError(
            f"--role {role} needs a single paged engine (dp replicas "
            "share no page pool); fix: drop dp= from --mesh or use "
            "--role both"
        )

    def construct(params_r, mesh=None, draft_params_r=None):
        mkw = dict(kw, mesh=mesh) if mesh is not None else kw
        paged_kw = dict(
            page_size=args.page_size, n_pages=args.n_pages,
            enable_prefix_cache=args.prefix_cache,
            **kv_kw,
        )
        if args.spec == "prompt-lookup":
            return load_adapters(PromptLookupPagedEngine(
                model, params_r, k=args.spec_k, ngram=args.spec_ngram,
                rounds_per_step=args.spec_rounds, **paged_kw, **mkw,
            ))
        if args.spec == "draft":
            return SpeculativePagedEngine(
                model, params_r, draft, draft_params_r,
                k=args.spec_k, rounds_per_step=args.spec_rounds,
                **paged_kw, **mkw,
            )
        if args.paged:
            return load_adapters(PagedEngine(
                model, params_r, **paged_kw, **mkw,
            ))
        return load_adapters(Engine(model, params_r, **mkw))

    if dp == 1 and tp == 1 and ep == 1:
        return construct(params, None, draft_params)

    import jax as _jax

    from shifu_tpu.parallel import MeshPlan, shard_params

    if dp == 1:
        mesh = MeshPlan.serving(tp=tp, ep=ep).build(
            _jax.devices()[: tp * ep]
        )
        return construct(
            shard_params(model, params, mesh), mesh,
            shard_params(draft, draft_params, mesh)
            if draft is not None else None,
        )
    from shifu_tpu.infer import build_replicated

    return build_replicated(
        lambda mesh: construct(
            shard_params(model, params, mesh), mesh,
            shard_params(draft, draft_params, mesh)
            if draft is not None else None,
        ),
        dp=dp, tp=tp, ep=ep,
    )


def _serve_fleet(args, spec: str) -> int:
    """``serve --fleet host:port,...``: this process is the ROUTER —
    no model, no device; it federates remote engine servers (each an
    ordinary ``serve`` on its host) behind the same HTTP front-end.
    The serving analogue of a multi-host training job's coordinator
    (fleet/bootstrap.py mirrors parallel/distributed.py)."""
    from shifu_tpu.fleet import build_fleet
    from shifu_tpu.infer import make_server
    from shifu_tpu.obs import SLOConfig, SLOWatchdog

    tok = _build_tokenizer(args)
    try:
        router = build_fleet(
            spec,
            ready_timeout_s=args.fleet_ready_timeout,
            require_all=args.fleet_require_all,
            probe_interval_s=args.fleet_probe_interval,
        )
    except (ValueError, RuntimeError) as e:
        print(str(e), file=sys.stderr)
        return 2
    watchdog = None
    slo_cfg = SLOConfig(
        p99_ttft_ms=args.slo_p99_ttft_ms,
        p99_itl_ms=args.slo_p99_itl_ms,
        max_step_ms=args.slo_max_step_ms,
        max_queue_depth=args.slo_max_queue,
    )
    if slo_cfg.active():
        watchdog = SLOWatchdog(slo_cfg)
    # Fleet SLO engine (obs/slo.py): declared per-tier burn-rate
    # budgets evaluated over the federated metrics pool, served on
    # GET /sloz; breaches capture rate-limited cross-host incident
    # bundles (obs/incident.py) under --incident-dir.
    monitor = None
    if args.slo_tier:
        from shifu_tpu.obs import IncidentWriter, SLOEngine, SLOMonitor
        from shifu_tpu.obs import parse_budget_spec

        try:
            budgets = [parse_budget_spec(s) for s in args.slo_tier]
            slo = SLOEngine(
                budgets,
                fast_window_s=args.slo_fast_window,
                slow_window_s=args.slo_slow_window,
                sample_interval_s=args.slo_sample_interval,
                metrics=router.metrics,
                flight=router.flight,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        incident = IncidentWriter(
            args.incident_dir,
            min_interval_s=args.incident_min_interval,
            metrics=router.metrics,
            flight=router.flight,
        )
        router.set_slo(slo, incident)
        monitor = SLOMonitor(
            router.slo_report, interval_s=args.slo_sample_interval,
        )
        monitor.start()
    server = make_server(
        router,
        host=args.host,
        port=args.port,
        tokenizer=tok,
        default_max_new=args.max_new_tokens,
        trace_log=args.trace_log,
        watchdog=watchdog,
        flight_dump=args.flight_dump,
        batch_backlog=args.batch_backlog,
    )
    print(
        json.dumps(
            {
                "serving": f"http://{args.host}:{server.server_port}",
                "engine": "FleetRouter",
                "backends": [b.addr for b in router.backends],
                "slo_tiers": list(args.slo_tier or ()),
            }
        ),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        server.runner.shutdown()
        router.prober.stop()
        if monitor is not None:
            monitor.stop()
    return 0


def cmd_serve(args) -> int:
    import os

    from shifu_tpu.infer import make_server

    fleet_spec = args.fleet or os.environ.get("SHIFU_FLEET")
    if fleet_spec:
        return _serve_fleet(args, fleet_spec)
    if args.slo_tier:
        print(
            "--slo-tier declares FLEET tier budgets and needs --fleet; "
            "ignored here (the per-host watchdog uses --slo-p99-*)",
            file=sys.stderr,
        )
    model = _build_model(args)
    params = _restore_params(args, model)
    tok = _build_tokenizer(args)
    if tok.vocab_size > model.cfg.vocab_size:
        print(
            f"warning: tokenizer vocab {tok.vocab_size} exceeds model "
            f"vocab {model.cfg.vocab_size}; out-of-range prompt ids "
            "reach the embedding unclipped (XLA clamps them) — train "
            "the model with a matching vocab",
            file=sys.stderr,
        )
    try:
        engine = build_serve_engine(args, model, params, tok)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.kv == "int8":
        # Operator hint (VERDICT next-round #8): the capacity-vs-
        # latency trade has a measured middle ground — see the
        # decision table in docs/observability.md.
        print(
            "hint: --kv int8 halves KV bytes (capacity) but costs "
            "decode latency (1.2B measured: bf16 4.72 ms/step, "
            "int8-KV 5.21, int8-KV+bf16-scales 4.23); consider "
            "--kv int8-b16s — docs/observability.md, 'KV-quant "
            "decision table'",
            file=sys.stderr,
        )
    watchdog = None
    from shifu_tpu.obs import SLOConfig, SLOWatchdog

    slo_cfg = SLOConfig(
        p99_ttft_ms=args.slo_p99_ttft_ms,
        p99_itl_ms=args.slo_p99_itl_ms,
        max_step_ms=args.slo_max_step_ms,
        max_queue_depth=args.slo_max_queue,
    )
    if slo_cfg.active():
        watchdog = SLOWatchdog(slo_cfg)
    server = make_server(
        engine,
        host=args.host,
        port=args.port,
        tokenizer=tok,
        default_max_new=args.max_new_tokens,
        trace_log=args.trace_log,
        watchdog=watchdog,
        flight_dump=args.flight_dump,
        model_id=args.model_id,
        ckpt_path=args.ckpt_dir,
        batch_backlog=args.batch_backlog,
        tune_table=args.tune_table,
        role=getattr(args, "role", "both") or "both",
    )
    print(
        json.dumps(
            {
                "serving": f"http://{args.host}:{server.server_port}",
                "engine": type(engine).__name__,
                "slots": args.max_slots,
                "max_len": args.max_len,
            }
        ),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        server.runner.shutdown()
    return 0


def cmd_batch(args) -> int:
    """``shifu_tpu batch run --input X.jsonl --output Y.jsonl
    [--router URL]`` — offline batch inference (shifu_tpu/batch).

    Reads an OpenAI-Batch-shaped JSONL, runs every line at
    ``tier="batch"`` (backfilling around interactive traffic through
    the engine's two-tier queue), and writes an OpenAI-compatible
    output JSONL plus a per-line error file. Progress journals durably
    (fsync + atomic rename): a SIGKILLed run rerun with the same paths
    RESUMES, emitting exactly one output record per ``custom_id``.
    With ``--router`` the lines go to a live server or fleet-router
    front-end (which shards them across its backends); without it an
    in-process engine is built from the same flags ``serve`` takes.
    SIGINT/SIGTERM stop gracefully (in-flight lines finish and
    journal; exit 1 with status "cancelled"). Exit 0 only on a
    completed job."""
    import signal
    import threading

    from shifu_tpu.batch import BatchRunner, JournalError

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # not the main thread (embedded use)

    server = None
    if args.router:
        base_url = args.router
    else:
        from shifu_tpu.infer import make_server

        model = _build_model(args)
        params = _restore_params(args, model)
        tok = _build_tokenizer(args)
        try:
            engine = build_serve_engine(args, model, params, tok)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        server = make_server(
            engine, port=0, tokenizer=tok,
            default_max_new=args.max_new_tokens,
            batch_backlog=args.batch_backlog,
            enable_batch_api=False,  # this process IS the job
        )
        threading.Thread(
            target=server.serve_forever, daemon=True
        ).start()
        base_url = f"http://127.0.0.1:{server.server_port}"

    try:
        runner = BatchRunner(
            args.input, args.output, base_url=base_url,
            error_path=args.error_file, journal_dir=args.journal,
            tier=args.tier, max_in_flight=args.max_in_flight,
            request_timeout_s=args.request_timeout,
            fsync_every=args.fsync_every, stop=stop,
        )
        try:
            report = runner.run()
        except (JournalError, OSError) as e:
            print(str(e), file=sys.stderr)
            return 2
        print(json.dumps(report))
        return 0 if report.get("status") == "completed" else 1
    finally:
        if server is not None:
            server.shutdown()
            server.runner.shutdown()


def cmd_fleet(args) -> int:
    """``shifu_tpu fleet rollout|snapshot|autoscale`` — fleet
    administration.

    ``rollout --ckpt PATH --router URL [--max-unavailable N]
    [--abort-on-slo]``: zero-downtime rolling weight rollout across the
    live router's roster — drain one wave at a time (``POST /drainz``
    with ``detach:false``), hot-swap each backend's weights (``POST
    /reloadz`` — manifest checkpoints are checksum-verified; a torn
    artifact 503s and halts the rollout with the old weights still
    serving), readiness-gate (``/healthz`` + ``/v1/models`` reporting
    the target ckpt), resume — with the router's SLO watchdog verdict
    as the automatic brake (a p99 budget breach pauses the wave;
    ``--abort-on-slo`` rolls updated backends back instead). Exit 0 on
    a complete rollout, 1 on failed/aborted (the printed report names
    which backends serve what), 2 on unusable configuration.

    ``snapshot --ckpt-dir ORBAX_DIR --out PARAMS_DIR``: convert a
    training checkpoint into the manifest params format
    (params-only, per-array sha256, atomically committed) — the
    artifact ``rollout``/``/reloadz`` verifies before swapping.

    ``autoscale --router URL [--standby host:port,...]
    [--envelope hbm=F,step_ms=MS] [--low-headroom F --high-headroom F
    --dwell S --tick S --flip-margin R --min-backends N] [--ticks N]``:
    the elastic-fleet control loop (fleet/autoscale.py) — polls
    ``/sloz`` + ``/statz`` and activates/parks standby hosts on the
    headroom hysteresis band, flips one host's prefill/decode role
    when the measured demand mix shifts past the margin
    (drain -> ``POST /rolez`` -> readiness gate -> resume), and paces
    batch admission against the declared envelope. ``--check``
    validates the flags offline (one-line fix hints; exit 0/1) — the
    fast CLI gate, like ``tune --check`` / ``loadgen --check``. Exit 0
    on a clean stop, 1 when any actuator failed along the way, 2 on
    unusable configuration."""
    if args.action == "autoscale":
        return _fleet_autoscale(args)
    if args.action == "snapshot":
        from shifu_tpu.checkpoint import save_params_dir

        if not args.ckpt_dir or not args.out:
            print("snapshot needs --ckpt-dir and --out", file=sys.stderr)
            return 2
        model = _build_model(args)
        params = _restore_params(args, model)
        try:
            out = save_params_dir(args.out, params)
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 2
        import jax as _jax

        n = sum(
            x.size for x in _jax.tree_util.tree_leaves(params)
        )
        print(json.dumps({"snapshot": out, "params": int(n)}))
        return 0

    # rollout
    from shifu_tpu.fleet import (
        RolloutController,
        RolloutError,
        RouterAdmin,
    )

    if not args.ckpt:
        print("rollout needs --ckpt PATH", file=sys.stderr)
        return 2
    admin = RouterAdmin(args.router)
    try:
        ctl = RolloutController(
            admin, args.ckpt,
            max_unavailable=args.max_unavailable,
            abort_on_slo=args.abort_on_slo,
            drain_timeout_s=args.drain_timeout,
            ready_timeout_s=args.ready_timeout,
            pause_timeout_s=args.pause_timeout,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    try:
        report = ctl.run()
    except RolloutError as e:
        print(json.dumps({"status": "failed", "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0 if report.get("status") == "complete" else 1


def _fleet_autoscale(args) -> int:
    """``shifu_tpu fleet autoscale`` — see :func:`cmd_fleet`."""
    from shifu_tpu.fleet import (
        AutoscaleController,
        AutoscaleError,
        AutoscalePolicy,
        RouterAdmin,
        check_policy,
        parse_envelope_spec,
        parse_fleet,
    )

    policy_kw = {
        "low_headroom": args.low_headroom,
        "high_headroom": args.high_headroom,
        "dwell_s": args.dwell,
        "tick_s": args.tick,
        "flip_margin": args.flip_margin,
        "min_backends": args.min_backends,
    }
    if args.check:
        ok, report = check_policy(
            policy_kw, standby=args.standby, envelope=args.envelope
        )
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    try:
        policy = AutoscalePolicy(**policy_kw)
        standby = parse_fleet(args.standby) if args.standby else []
        envelope = (
            parse_envelope_spec(args.envelope) if args.envelope else None
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    ctl = AutoscaleController(
        RouterAdmin(args.router),
        standby=standby, policy=policy, envelope=envelope,
        ready_timeout_s=args.ready_timeout,
        drain_timeout_s=args.drain_timeout,
        max_ticks=args.ticks,
    )
    try:
        report = ctl.run()
    except AutoscaleError as e:
        print(json.dumps({"status": "failed", "error": str(e)}))
        return 1
    except KeyboardInterrupt:
        ctl.stop()
        report = dict(ctl.report)
        report["status"] = "interrupted"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    return 0 if report.get("failures", 0) == 0 else 1


def cmd_trace(args) -> int:
    """``shifu_tpu trace export``: Chrome trace-event JSON from either
    source — a local ``serve --trace-log`` JSONL (``--in``), or a LIVE
    router/server's ``GET /tracez`` (``--url`` + ``--trace-id``), which
    merges every host's span log for one distributed trace into a
    single timeline with a process lane per (host, replica) and the
    probe-estimated clock offsets applied. Loadable in chrome://tracing
    or Perfetto; the host-side complement to the device-side
    ``jax.profiler`` traces (docs/observability.md)."""
    if args.url:
        if not args.trace_id:
            print("--url requires --trace-id", file=sys.stderr)
            return 2
        import urllib.error

        from shifu_tpu.obs.disttrace import fetch_and_merge

        try:
            trace = fetch_and_merge(args.url, args.trace_id)
        except (OSError, ValueError, urllib.error.URLError) as e:
            print(str(e), file=sys.stderr)
            return 2
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(trace, f)
    elif args.infile:
        from shifu_tpu.obs.trace import export_trace_log

        try:
            trace = export_trace_log(args.infile, args.out)
        except OSError as e:
            print(str(e), file=sys.stderr)
            return 2
    else:
        print("trace export needs --in PATH or --url URL --trace-id ID",
              file=sys.stderr)
        return 2
    if args.out:
        events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        print(json.dumps({
            "out": args.out,
            "events": len(events),
            "requests": len({(e["pid"], e["tid"]) for e in events}),
        }))
    else:
        print(json.dumps(trace))
    return 0


def cmd_debug(args) -> int:
    """``shifu_tpu debug dump``: the flight-recorder ring as JSON —
    fetched from a live server's ``GET /debugz`` (``--url``), or the
    in-process global ring when embedding (no url). ``--out`` writes a
    file (the same shape the runner's crash auto-dump produces);
    otherwise the document prints to stdout."""
    if args.url:
        import urllib.error
        import urllib.request

        url = args.url.rstrip("/") + "/debugz"
        if args.last:
            url += f"?n={int(args.last)}"
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                data = json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"cannot fetch {url}: {e}", file=sys.stderr)
            return 2
    else:
        from shifu_tpu import obs

        data = {
            "capacity": obs.FLIGHT.capacity,
            "dropped": obs.FLIGHT.dropped,
            "events": obs.FLIGHT.snapshot(last=args.last),
        }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(data, f)
            f.write("\n")
        print(json.dumps({
            "out": args.out, "events": len(data.get("events", [])),
        }))
    else:
        print(json.dumps(data))
    return 0


def cmd_tune(args) -> int:
    """``shifu_tpu tune``: the persistent kernel autotuner.

    Times every applicable kernel variant per shape class for the
    requested legs (fwd+grad, best-of-N) and writes the winner table
    as a versioned artifact (``--out``, default kernels.tune.json)
    that serve/train/bench activate via ``--tune-table`` and ``obs
    check-tune`` diffs. ``--check`` skips all timing: validate the
    variant registry's completeness (and, with ``--table``, an
    existing artifact's schema + winners) — fast enough for tier-1."""
    from shifu_tpu.tune import (
        autotune,
        check_registry,
        check_table,
        load_table,
        save_table,
    )
    from shifu_tpu.tune.table import TuneTableError

    legs = tuple(
        s.strip() for s in args.legs.split(",") if s.strip()
    )
    try:
        from shifu_tpu.tune.autotune import tune_cases

        tune_cases(legs, preset=args.preset)  # validate before work
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.check:
        report = check_registry(legs, preset=args.preset)
        if args.table:
            try:
                table = load_table(args.table)
            except (OSError, TuneTableError) as e:
                report["problems"].append(f"{args.table}: {e}")
                report["status"] = "fail"
            else:
                import jax

                dev = jax.devices()[0]
                probs = check_table(
                    table,
                    device_kind=getattr(
                        dev, "device_kind", dev.platform
                    ),
                )
                report["table"] = {
                    "path": args.table,
                    "device_kind": table.device_kind,
                    "entries": len(table.entries),
                    "content_hash": table.content_hash(),
                }
                if probs:
                    report["problems"].extend(probs)
                    report["status"] = "fail"
        print(json.dumps(report, indent=2))
        return 0 if report["status"] == "ok" else 1
    table = autotune(legs, preset=args.preset, repeats=args.repeats)
    save_table(table, args.out)
    print(json.dumps({
        "out": args.out,
        "device_kind": table.device_kind,
        "legs": list(table.legs),
        "content_hash": table.content_hash(),
        "winners": {
            tok: e["variant"] for tok, e in sorted(table.entries.items())
        },
    }, indent=2))
    return 0


def cmd_loadgen(args) -> int:
    """``shifu_tpu loadgen``: the measurement harness (ROADMAP item
    6). Replays a declarative scenario mix at a fixed open-loop
    offered load against a live router or engine server, scrapes
    ``/sloz`` + ``/statz`` + the federated ``/metrics`` while
    driving, and exits with per-tier SLO verdicts (exit 0 = every
    tier held its budget, 1 = burning/breached, 2 = unusable
    scenario/flags). ``--check`` validates the scenario file alone —
    parse, mix weights, tier/budget sanity, chaos schedule — no
    traffic, fast enough for tier-1 (the ``tune --check`` pattern)."""
    from shifu_tpu.loadgen import (
        LoadRunner,
        ScenarioError,
        check_scenario,
        load_scenario,
    )

    if args.check:
        ok, report = check_scenario(args.scenario)
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    try:
        sc = load_scenario(args.scenario)
    except ScenarioError as e:
        print(json.dumps({
            "status": "fail", "problems": e.problems,
        }, indent=2), file=sys.stderr)
        return 2
    except OSError as e:
        print(f"cannot read scenario: {e}", file=sys.stderr)
        return 2
    if args.duration is not None:
        sc.duration_s = float(args.duration)
    if args.rate is not None:
        sc.rate_rps = float(args.rate)
    if args.seed is not None:
        sc.seed = int(args.seed)

    chaos = None
    if sc.chaos and not args.no_chaos:
        from shifu_tpu.fleet.chaos import ChaosTrack

        pids = {}
        for spec in args.chaos_pid or ():
            addr, _, pid = spec.rpartition("=")
            if not addr or not pid.isdigit():
                print(f"--chaos-pid wants ADDR=PID, got {spec!r}",
                      file=sys.stderr)
                return 2
            pids[addr] = int(pid)
        chaos = ChaosTrack(sc.chaos, url=args.url, pids=pids)

    runner = LoadRunner(
        sc, args.url,
        request_timeout_s=args.timeout,
        scrape_interval_s=args.scrape_interval,
        max_inflight=args.max_inflight,
        chaos=chaos,
    )
    report = runner.run()
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    if args.compact_out:
        # The flat lg_* row `obs check-bench --current` gates
        # directly (load_record accepts a raw compact line).
        with open(args.compact_out, "w", encoding="utf-8") as f:
            json.dump(report["compact"], f, indent=2)
    print(json.dumps(report, indent=2))
    return 0 if report["verdict"] == "pass" else 1


def cmd_obs(args) -> int:
    """``shifu_tpu obs check-bench``: gate a compact bench line against
    a recorded baseline (obs/benchgate.py). Exit 0 = within tolerance,
    1 = regression, 2 = unusable inputs. ``bench.py --baseline`` runs
    the same gate after a live bench.

    ``shifu_tpu obs check-tune``: diff two tune-table artifacts
    (--baseline old, --current new). Exit 0 = winners identical, 1 =
    winners changed / classes added or removed (reviewable fact), 2 =
    unusable artifacts.

    ``shifu_tpu obs check-docs``: drift gate between the registered
    ``shifu_*`` metric families (source scan of the package) and
    docs/observability.md — exit 1 when telemetry shipped undocumented
    or the doc names families no code registers.

    ``shifu_tpu obs incident list|show|export``: inspect the breach
    incident bundles a fleet router captured (obs/incident.py) —
    list summarises every bundle under ``--dir``, show prints one
    manifest with per-file summaries (``--id``), export packs a bundle
    into a ``.tar.gz`` (``--id`` + ``--out``).

    ``shifu_tpu obs top``: live terminal dashboard polling a router's
    /statz + /sloz (per-backend load/roles/health, tier burn rates);
    ``--once`` renders a single frame and exits (scriptable)."""
    if args.action == "incident":
        from shifu_tpu.obs import incident as _inc

        sub = args.sub or "list"
        if sub not in ("list", "show", "export"):
            print(f"unknown incident action {sub!r} "
                  "(list | show | export)", file=sys.stderr)
            return 2
        root = args.dir
        if sub == "list":
            print(json.dumps(_inc.list_incidents(root), indent=2))
            return 0
        if not args.id:
            print(f"obs incident {sub} requires --id", file=sys.stderr)
            return 2
        try:
            if sub == "show":
                print(json.dumps(
                    _inc.show_incident(root, args.id), indent=2,
                ))
                return 0
            out = args.out or f"{args.id}.tar.gz"
            path = _inc.export_incident(root, args.id, out)
            print(json.dumps({"exported": args.id, "out": path}))
            return 0
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 2
    if args.action == "top":
        from shifu_tpu.obs.top import run_top

        return run_top(
            args.url,
            interval_s=args.interval,
            iterations=1 if args.once else None,
            loadgen_path=args.loadgen,
        )
    if args.action == "check-docs":
        import shifu_tpu
        from shifu_tpu.obs.docscheck import check_docs

        pkg = os.path.dirname(os.path.abspath(shifu_tpu.__file__))
        doc = args.doc
        if doc is None:
            doc = os.path.join(os.path.dirname(pkg),
                               "docs", "observability.md")
        try:
            ok, report = check_docs(pkg, doc)
        except OSError as e:
            print(f"cannot scan: {e}", file=sys.stderr)
            return 2
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    if args.baseline is None or args.current is None:
        print(f"{args.action} requires --baseline and --current",
              file=sys.stderr)
        return 2
    if args.action == "check-tune":
        from shifu_tpu.obs.benchgate import check_tune

        try:
            ok, report = check_tune(args.baseline, args.current)
        except (OSError, ValueError) as e:
            print(f"cannot load tune tables: {e}", file=sys.stderr)
            return 2
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    from shifu_tpu.obs.benchgate import check_bench, load_record

    try:
        baseline = load_record(args.baseline)
        current = load_record(args.current)
    except (OSError, ValueError) as e:
        print(f"cannot load bench records: {e}", file=sys.stderr)
        return 2
    ok, report = check_bench(
        current, baseline, scale_tol=args.scale_tolerance
    )
    print(json.dumps(report, indent=2))
    return 0 if ok else 1


def cmd_info(args) -> int:
    import jax

    import shifu_tpu
    from shifu_tpu.data import native_available

    info = {
        "version": shifu_tpu.__version__,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "native_packer": native_available(),
    }
    print(json.dumps(info, indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="shifu_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    def model_flags(sp, *, schedule_default):
        sp.add_argument("--family", default="transformer",
                        choices=["transformer", "mamba"])
        sp.add_argument("--preset", default="tiny",
                        choices=["tiny", "small", "1b", "7b"])
        sp.add_argument("--moe-experts", type=int, default=0)
        sp.add_argument("--attn", choices=["xla", "flash", "ring"],
                        default=None)
        sp.add_argument("--optimizer", default="adamw",
                        choices=["adamw", "lion", "adafactor", "sgd"])
        sp.add_argument("--schedule", default=schedule_default,
                        choices=["constant", "cosine", "linear", "wsd",
                                 "inverse_sqrt"])
        sp.add_argument("--lr", type=float, default=3e-4)
        sp.add_argument("--warmup", type=int, default=0)
        sp.add_argument("--ckpt-dir")
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--tune-table",
                        help="kernel tune-table artifact (shifu_tpu "
                             "tune output): per-shape-class kernel "
                             "variants chosen by measurement; schema/"
                             "device mismatch warns and runs v0 "
                             "defaults")

    t = sub.add_parser("train", help="run the training loop")
    model_flags(t, schedule_default="cosine")
    t.add_argument("--data", help="dataset dir (write_shards layout)")
    t.add_argument(
        "--synthetic",
        action="store_true",
        help="random-token data (the default when --data is omitted)",
    )
    t.add_argument("--steps", type=int, default=100)
    t.add_argument("--batch-size", type=int, default=8)
    t.add_argument("--seq-len", type=int, default=513)
    t.add_argument("--microbatches", type=int, default=None)
    t.add_argument("--mesh", help="e.g. fsdp=4,tp=2 (axes of MeshPlan)")
    t.add_argument("--ckpt-every", type=int, default=1000)
    t.add_argument("--metrics", help="JSONL metrics path")
    t.add_argument("--log-every", type=int, default=10)
    t.set_defaults(fn=cmd_train)

    e = sub.add_parser(
        "eval",
        help="evaluate: perplexity (ppl), multiple-choice logprob "
             "scoring (mc), or greedy exact-match generation (gen)",
    )
    model_flags(e, schedule_default="constant")
    e.add_argument("--task", default="ppl", choices=["ppl", "mc", "gen"])
    e.add_argument("--data", required=True,
                   help="ppl: dataset dir (write_shards layout); "
                        'mc: JSONL {"context","options","answer"}; '
                        'gen: JSONL {"prompt","answers"}')
    e.add_argument("--tokenizer", help="bpe-train artifact for mc/gen "
                                       "(default: byte tokenizer)")
    e.add_argument("--batch-size", type=int, default=8,
                   help="ppl batch / mc scoring rows per forward")
    e.add_argument("--seq-len", type=int, default=513,
                   help="ppl/mc row length; gen: engine max_len")
    e.add_argument("--batches", type=int, default=32, help="ppl only")
    e.add_argument("--max-new-tokens", type=int, default=64,
                   help="gen decode budget")
    e.add_argument("--max-slots", type=int, default=8,
                   help="gen engine concurrency")
    e.add_argument("--predictions", action="store_true",
                   help="gen: include decoded predictions in the JSON")
    e.set_defaults(fn=cmd_eval)

    d = sub.add_parser(
        "dpo", help="DPO preference tuning from a JSONL of pairs"
    )
    model_flags(d, schedule_default="constant")
    d.add_argument("--data", required=True,
                   help='JSONL: {"prompt", "chosen", "rejected"} — '
                        "token-id lists, or strings with --tokenizer")
    d.add_argument("--tokenizer", help="bpe-train artifact (bpe.json)")
    d.add_argument("--steps", type=int, default=100)
    d.add_argument("--batch-size", type=int, default=8)
    d.add_argument("--seq-len", type=int, default=512)
    d.add_argument("--beta", type=float, default=0.1)
    d.add_argument("--loss-type", default="sigmoid",
                   choices=["sigmoid", "ipo"])
    d.add_argument("--mesh", help="e.g. fsdp=4,tp=2 (axes of MeshPlan)")
    d.add_argument("--out-ckpt-dir", help="save the tuned state here")
    d.add_argument("--log-every", type=int, default=10)
    d.set_defaults(fn=cmd_dpo)

    kd = sub.add_parser(
        "distill",
        help="knowledge distillation from a teacher checkpoint "
             "(teacher top-k annotations + sharded student training)",
    )
    model_flags(kd, schedule_default="constant")
    kd.add_argument("--data", required=True,
                    help='JSONL: {"text": str} or {"tokens": [ids]}')
    kd.add_argument("--tokenizer", help="bpe-train artifact (bpe.json)")
    kd.add_argument("--teacher-preset", required=True,
                    choices=["tiny", "small", "1b", "7b"])
    kd.add_argument("--teacher-ckpt-dir",
                    help="teacher weights (omit for a random teacher — "
                         "only useful in tests)")
    kd.add_argument("--steps", type=int, default=100)
    kd.add_argument("--batch-size", type=int, default=8)
    kd.add_argument("--seq-len", type=int, default=512)
    kd.add_argument("--alpha", type=float, default=0.5,
                    help="CE weight; (1-alpha) weights the KD term")
    kd.add_argument("--kd-temperature", type=float, default=2.0)
    kd.add_argument("--kd-top-k", type=int, default=32)
    kd.add_argument("--mesh", help="e.g. fsdp=4,tp=2 (axes of MeshPlan)")
    kd.add_argument("--out-ckpt-dir", help="save the distilled state")
    kd.add_argument("--log-every", type=int, default=10)
    kd.set_defaults(fn=cmd_distill)

    r = sub.add_parser(
        "grpo",
        help="online RL (GRPO) with a contains-target verifiable reward",
    )
    model_flags(r, schedule_default="constant")
    r.add_argument("--data", required=True,
                   help='JSONL: {"prompt": str|ids, "target": str} — '
                        "reward 1 when the decoded completion contains "
                        "the target substring")
    r.add_argument("--tokenizer", help="bpe-train artifact (bpe.json); "
                                       "default: byte tokenizer")
    r.add_argument("--steps", type=int, default=50,
                   help="rollout+update rounds")
    r.add_argument("--group-size", type=int, default=8)
    r.add_argument("--prompts-per-step", type=int, default=4)
    r.add_argument("--max-new-tokens", type=int, default=32)
    r.add_argument("--seq-len", type=int, default=256,
                   help="packed row width / engine max_len")
    r.add_argument("--max-slots", type=int, default=16,
                   help="rollout engine concurrency")
    r.add_argument("--temperature", type=float, default=1.0,
                   help="rollout sampling temperature (must be > 0 — "
                        "greedy groups have no variance)")
    r.add_argument("--beta", type=float, default=0.0,
                   help="KL-to-reference coefficient (0 skips the "
                        "reference forward entirely)")
    r.add_argument("--clip-eps", type=float, default=0.2)
    r.add_argument("--mesh", help="e.g. fsdp=4 (axes of MeshPlan)")
    r.add_argument("--out-ckpt-dir", help="save the tuned state here")
    r.add_argument("--log-every", type=int, default=5)
    r.set_defaults(fn=cmd_grpo)

    g = sub.add_parser("generate", help="text completion from a checkpoint")
    model_flags(g, schedule_default="constant")
    g.add_argument("--prompt", required=True)
    g.add_argument("--tokenizer", help="bpe-train artifact (bpe.json); "
                                       "default: byte tokenizer")
    g.add_argument("--max-new-tokens", type=int, default=128)
    g.add_argument("--temperature", type=float, default=0.8)
    g.add_argument("--top-p", type=float, default=0.95)
    g.set_defaults(fn=cmd_generate)

    b = sub.add_parser(
        "bpe-train", help="train a byte-level BPE tokenizer (native core)"
    )
    b.add_argument("--data", nargs="+", required=True,
                   help="text file(s); whole-file docs unless --per-line")
    b.add_argument("--per-line", action="store_true",
                   help="treat each line as one document")
    b.add_argument("--vocab-size", type=int, default=8192)
    b.add_argument("--out", required=True, help="output bpe.json path")
    b.set_defaults(fn=cmd_bpe_train)

    def engine_flags(sp):
        """The serving-ENGINE flag surface, shared by `serve` and
        `batch` (batch's in-process mode builds the same engine via
        build_serve_engine — one seam, one flag set)."""
        sp.add_argument("--tokenizer",
                        help="bpe-train artifact (bpe.json); "
                             "default: byte tokenizer")
        sp.add_argument("--max-slots", type=int, default=8)
        sp.add_argument("--max-len", type=int, default=2048)
        sp.add_argument("--max-new-tokens", type=int, default=128)
        sp.add_argument("--temperature", type=float, default=0.8)
        sp.add_argument("--top-p", type=float, default=0.95)
        sp.add_argument("--decode-chunk", type=int, default=8,
                        help="tokens decoded per host round-trip (1 = "
                             "sync every token; higher amortises "
                             "dispatch latency at the cost of "
                             "chunk-granular admission)")
        sp.add_argument("--eos-id", type=int, default=None,
                        help="stop token id (default: byte-tokenizer "
                             "eos; -1 disables eos stopping)")
        sp.add_argument("--paged", action="store_true",
                        help="paged KV pool instead of dense per-slot "
                             "cache")
        sp.add_argument("--page-size", type=int, default=64)
        sp.add_argument("--n-pages", type=int, default=None,
                        help="pool size (default: dense-equivalent)")
        sp.add_argument("--prefix-cache", action="store_true",
                        help="share page-aligned prompt prefixes "
                             "across requests (paged only)")
        sp.add_argument("--per-request-sampling", action="store_true",
                        help="honour per-request temperature/top_k/"
                             "top_p/min_p fields (traced per-slot "
                             "sampler; costs one vocab partial-sort "
                             "per row per step)")
        sp.add_argument("--penalties", action="store_true",
                        help="honour presence/frequency/repetition "
                             "penalty fields (slots x vocab count "
                             "buffer; implies --per-request-sampling)")
        sp.add_argument("--logit-bias", action="store_true",
                        help="honour logit_bias / allowed_token_ids "
                             "fields (slots x vocab f32 bias buffer; "
                             "implies --per-request-sampling)")
        sp.add_argument("--kv", default="bf16",
                        choices=["bf16", "int8", "int8-b16s"],
                        help="KV-cache dtype for the paged pool: int8 "
                             "halves KV bytes (capacity) at a decode-"
                             "latency cost; int8-b16s narrows the "
                             "scales to bf16 and recovers most of it "
                             "(decision table: docs/observability.md)")
        sp.add_argument("--kv-tier", default="off",
                        choices=["off", "host"],
                        help="host-RAM tier for the prefix cache: "
                             "evicted prefix pages spill to pinned "
                             "host memory and restore asynchronously "
                             "on a later hit — when the measured "
                             "restore beats recomputing the prefill "
                             "(needs --prefix-cache; "
                             "docs/kv_tiering.md)")
        sp.add_argument("--kv-host-bytes", type=_size_bytes,
                        default="4g",
                        help="host-tier byte budget (LRU beyond it); "
                             "accepts 512m/4g/… suffixes "
                             "(--kv-tier host only)")
        sp.add_argument("--kv-disk-bytes", type=_size_bytes,
                        default=0,
                        help="disk tier below the host tier: evicted "
                             "host entries demote to mmap'd SKVP "
                             "segment files (LRU beyond the budget), "
                             "torn segments are refused by checksum "
                             "and survivors are reused after a "
                             "restart; accepts 512m/4g/… suffixes "
                             "(needs --kv-tier host and --kv-disk-dir)")
        sp.add_argument("--kv-disk-dir",
                        help="directory for the disk tier's segment "
                             "files (must exist and be writable; one "
                             "engine per directory)")
        sp.add_argument("--kv-export-slots", type=int, default=64,
                        help="live /kv/pages export records kept for "
                             "peer pickup (rid -> page chain, FIFO "
                             "beyond it); migration-heavy fleets size "
                             "this up so a session's export survives "
                             "the turn's think-time "
                             "(--kv-tier host only)")
        sp.add_argument("--role", default="both",
                        choices=["prefill", "decode", "both"],
                        help="disaggregation role advertised on "
                             "/healthz + /v1/models: a fleet router "
                             "sends prefill-heavy admissions to "
                             "prefill hosts and migrates their paged "
                             "KV to decode hosts over /kv/pages "
                             "(prefill needs --paged --prefix-cache "
                             "--kv-tier host; docs/architecture.md)")
        sp.add_argument("--mesh",
                        help="serving mesh, e.g. dp=2,tp=2 or "
                             "tp=2,ep=2: tp shards heads/mlp, ep "
                             "shards MoE expert weights (instead of "
                             "replicating them), dp model replicas "
                             "behind one router (dp x tp x ep devices "
                             "total)")
        sp.add_argument("--lora-ckpt-dir", action="append",
                        help="LoRA adapter checkpoint dir (repeatable; "
                             "adapter ids are assigned 1..n in flag "
                             'order; requests pick one via the '
                             '"adapter" field)')
        sp.add_argument("--lora-rank", type=int, default=8)
        sp.add_argument("--lora-alpha", type=float, default=16.0)
        sp.add_argument("--lora-targets", default="wq,wk,wv,wo")
        sp.add_argument("--spec", default="off",
                        choices=["off", "prompt-lookup", "draft"],
                        help="speculative decoding: prompt-lookup "
                             "proposes each request's own n-gram "
                             "continuations (no draft model — wins on "
                             "repetitive/structured text); draft uses "
                             "a trained draft model")
        sp.add_argument("--spec-k", type=int, default=8,
                        help="proposed tokens per round")
        sp.add_argument("--spec-ngram", type=int, default=3,
                        help="prompt-lookup match length")
        sp.add_argument("--spec-rounds", type=int, default=8,
                        help="rounds per dispatch (the speculative "
                             "analogue of --decode-chunk)")
        sp.add_argument("--draft-preset",
                        choices=["tiny", "small", "1b", "7b"],
                        help="draft model preset (--spec draft)")
        sp.add_argument("--draft-ckpt-dir",
                        help="draft checkpoint (--spec draft)")

    s = sub.add_parser("serve", help="HTTP completions server")
    model_flags(s, schedule_default="constant")
    engine_flags(s)
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--batch-backlog", type=int, default=None,
                   help="admission cap for tier=\"batch\" requests: "
                        "arrivals while the engine's batch backlog is "
                        "at/over this depth get 429 + Retry-After "
                        "(default: uncapped). The offline batch tier's "
                        "OOM guard — shifu_tpu/batch")
    s.add_argument("--trace-log",
                   help="append one JSON line per completed request "
                        "(timing spans) to this file")
    s.add_argument("--slo-p99-ttft-ms", type=float, default=None,
                   help="SLO budget: p99 TTFT over the rolling "
                        "completion window; breach flips /healthz to "
                        "degraded with a reason")
    s.add_argument("--slo-p99-itl-ms", type=float, default=None,
                   help="SLO budget: p99 per-request mean inter-token "
                        "latency (windowed)")
    s.add_argument("--slo-max-step-ms", type=float, default=None,
                   help="SLO budget: p99 engine-step wall time over "
                        "the flight ring's recent steps")
    s.add_argument("--slo-max-queue", type=int, default=None,
                   help="SLO budget: engine queue + runner inbox depth")
    s.add_argument("--flight-dump",
                   help="write the flight-recorder ring here if the "
                        "engine thread dies (default: a pid-stamped "
                        "file in the temp dir)")
    s.add_argument("--model-id",
                   help="the id /v1/models advertises (default: the "
                        "model class name, e.g. 'transformer'). A "
                        "multi-model fleet routes requests by it — "
                        "give each backend tier a distinct name "
                        "(gemma2-flash, mixtral-ep, mamba) and the "
                        "router 404s unknown ids")
    s.add_argument("--fleet",
                   help="ROUTER mode: comma-separated backend roster "
                        "host:port,... (or SHIFU_FLEET env var). This "
                        "process builds no model/engine — it federates "
                        "remote `serve` hosts behind one HTTP surface "
                        "with health-aware least-loaded routing, "
                        "retries with a budget, circuit breakers, and "
                        "POST /drainz graceful draining (shifu_tpu/"
                        "fleet; docs/architecture.md)")
    s.add_argument("--fleet-probe-interval", type=float, default=2.0,
                   help="seconds between backend /healthz re-probes "
                        "(dead backends rejoin within one interval of "
                        "recovering)")
    s.add_argument("--fleet-ready-timeout", type=float, default=60.0,
                   help="startup readiness gate: how long to wait for "
                        "backends' /healthz before serving (default: "
                        "start when ANY backend is ready)")
    s.add_argument("--fleet-require-all", action="store_true",
                   help="readiness gate requires EVERY roster entry "
                        "(default: any one backend suffices; the "
                        "prober brings stragglers in later)")
    s.add_argument("--slo-tier", action="append", default=None,
                   metavar="TIER:BUDGETS",
                   help="ROUTER mode: declare one admission tier's SLO "
                        "budget for the fleet SLO engine, e.g. "
                        "'interactive:ttft=250,itl=40,err=0.01' "
                        "(keys: ttft/itl p99 ms, err allowed error-"
                        "rate, objective latency compliance target, "
                        "default 0.99). Repeatable (one per tier). "
                        "Serves GET /sloz with multi-window burn "
                        "rates + headroom and captures incident "
                        "bundles on breach")
    s.add_argument("--slo-fast-window", type=float, default=60.0,
                   help="fleet SLO fast burn window seconds (the "
                        "'burning' early-warning window)")
    s.add_argument("--slo-slow-window", type=float, default=900.0,
                   help="fleet SLO slow burn window seconds (breached "
                        "requires this window over budget with full "
                        "coverage)")
    s.add_argument("--slo-sample-interval", type=float, default=5.0,
                   help="seconds between federated-pool snapshots / "
                        "background SLO evaluations")
    s.add_argument("--incident-dir", default="incidents",
                   help="where breach incident bundles are written "
                        "(timestamped directory + manifest each; "
                        "inspect with `shifu_tpu obs incident`)")
    s.add_argument("--incident-min-interval", type=float, default=900.0,
                   help="rate limit: minimum seconds between incident "
                        "bundles (a flapping budget produces one "
                        "bundle per quiet period, not one per tick)")
    s.set_defaults(fn=cmd_serve)

    bt = sub.add_parser(
        "batch",
        help="offline batch inference (shifu_tpu/batch): run an "
             "OpenAI-Batch-shaped JSONL through a serving endpoint — "
             "file in, file out, resumable. `--router URL` sends the "
             "lines to a live server/fleet router at tier=\"batch\" "
             "(backfilling around its interactive traffic); without "
             "it an in-process engine is built from the same flags "
             "`serve` takes. SIGKILL-safe: progress journals durably "
             "and a rerun with the same paths resumes with exactly "
             "one output record per custom_id",
    )
    bt.add_argument("action", choices=["run"])
    model_flags(bt, schedule_default="constant")
    engine_flags(bt)
    bt.add_argument("--input", required=True,
                    help="input JSONL: one OpenAI-Batch line per "
                         "request ({custom_id, method, url, body})")
    bt.add_argument("--output", required=True,
                    help="output JSONL path (written atomically at "
                         "the end; exactly one record per custom_id)")
    bt.add_argument("--error-file",
                    help="per-line failure records (default: "
                         "<output>.errors.jsonl)")
    bt.add_argument("--journal",
                    help="progress journal directory (default: "
                         "<output>.journal). Reruns resume from it; "
                         "it refuses a different input file")
    bt.add_argument("--router",
                    help="live serving endpoint URL (a single server "
                         "or a fleet router front-end); omit to build "
                         "an in-process engine from the model flags")
    bt.add_argument("--max-in-flight", type=int, default=32,
                    help="bounded in-flight request window")
    bt.add_argument("--request-timeout", type=float, default=300.0)
    bt.add_argument("--fsync-every", type=int, default=1,
                    help="fsync the journal every N records (1 = "
                         "strict, every record)")
    bt.add_argument("--tier", default="batch",
                    choices=["batch", "interactive"],
                    help="admission tier the lines ride (batch "
                         "backfills around live traffic)")
    bt.add_argument("--batch-backlog", type=int, default=None,
                    help="in-process mode: the local server's batch "
                         "admission cap (429 + Retry-After past it)")
    bt.set_defaults(fn=cmd_batch)

    fl = sub.add_parser(
        "fleet",
        help="fleet administration: `rollout` walks a zero-downtime "
             "rolling weight rollout across a live router's roster "
             "(drain -> POST /reloadz hot-swap -> readiness gate -> "
             "resume, SLO watchdog as the brake); `snapshot` converts "
             "a training checkpoint into the checksum-manifest params "
             "format the rollout verifies; `autoscale` runs the "
             "elastic-fleet control loop (SLO-headroom scaling over a "
             "standby pool, prefill/decode role rebalancing, "
             "envelope-paced batch backfill)",
    )
    fl.add_argument("action", choices=["rollout", "snapshot", "autoscale"])
    model_flags(fl, schedule_default="constant")  # snapshot model build
    fl.add_argument("--router", default="http://127.0.0.1:8000",
                    help="the live fleet router's base URL (rollout "
                         "drives it through /statz, /drainz, and "
                         "/rolloutz)")
    fl.add_argument("--ckpt",
                    help="rollout target checkpoint PATH as seen by "
                         "the BACKEND hosts: a manifest params dir "
                         "(fleet snapshot; checksum-verified on "
                         "reload) or an orbax checkpoint dir")
    fl.add_argument("--max-unavailable", type=int, default=1,
                    help="backends drained+reloading at once (the "
                         "wave size); the rest keep serving")
    fl.add_argument("--abort-on-slo", action="store_true",
                    help="on an SLO budget breach, roll already-"
                         "updated backends back to their previous "
                         "checkpoint (default: pause the wave until "
                         "the verdict clears or --pause-timeout)")
    fl.add_argument("--drain-timeout", type=float, default=120.0,
                    help="seconds to wait for a draining backend's "
                         "in-flight streams")
    fl.add_argument("--ready-timeout", type=float, default=60.0,
                    help="post-reload readiness gate (healthz + "
                         "/v1/models reporting the target ckpt)")
    fl.add_argument("--pause-timeout", type=float, default=300.0,
                    help="how long a paused wave waits for the SLO "
                         "verdict to clear before the rollout fails")
    fl.add_argument("--out", help="snapshot: output params-dir path; "
                    "autoscale: also write the run report JSON here")
    fl.add_argument("--standby", default=None,
                    help="autoscale: parked host pool as "
                         "host:port,... — low SLO headroom activates "
                         "the next one (readiness-gated, peer-warmed); "
                         "fat headroom parks the emptiest back")
    fl.add_argument("--envelope", default=None,
                    help="autoscale: declared serving envelope, e.g. "
                         "hbm=0.85,step_ms=120[,ramp=0.8] — batch "
                         "admission is paced against it fleet-wide")
    fl.add_argument("--low-headroom", type=float, default=0.15,
                    help="autoscale: min per-tier SLO headroom below "
                         "which a standby host is activated")
    fl.add_argument("--high-headroom", type=float, default=0.60,
                    help="autoscale: headroom above which the "
                         "emptiest activated standby is parked")
    fl.add_argument("--dwell", type=float, default=60.0,
                    help="autoscale: min seconds between pool/role "
                         "actions (the anti-flap brake; must exceed "
                         "--tick)")
    fl.add_argument("--tick", type=float, default=5.0,
                    help="autoscale: control-loop period seconds")
    fl.add_argument("--flip-margin", type=float, default=2.0,
                    help="autoscale: how many times busier one role's "
                         "hosts must measure than the other's before "
                         "a drain-flip-resume role change")
    fl.add_argument("--min-backends", type=int, default=1,
                    help="autoscale: active-pool floor — scale-down "
                         "and role flips never go below it")
    fl.add_argument("--ticks", type=int, default=None,
                    help="autoscale: stop after N ticks (default: "
                         "run until interrupted)")
    fl.add_argument("--check", action="store_true",
                    help="autoscale: validate the policy flags, "
                         "standby roster, and envelope spec (no "
                         "network) and exit 0/1 — the tier-1 CLI gate")
    fl.set_defaults(fn=cmd_fleet)

    tr = sub.add_parser(
        "trace",
        help="serving request traces: export a serve --trace-log JSONL "
             "— or one distributed trace from a live router's /tracez "
             "(--url + --trace-id) — as Chrome trace-event JSON "
             "(chrome://tracing / Perfetto)",
    )
    tr.add_argument("action", choices=["export"])
    tr.add_argument("--in", dest="infile",
                    help="trace-log JSONL path (serve --trace-log)")
    tr.add_argument("--url",
                    help="router/server base URL — fetch GET /tracez "
                         "and merge every host's spans for --trace-id "
                         "into one timeline (clock offsets applied)")
    tr.add_argument("--trace-id",
                    help="the distributed trace id (from the "
                         "x-shifu-trace response header or a "
                         "completion's timing block)")
    tr.add_argument("--out",
                    help="write the Chrome trace JSON here "
                         "(default: print to stdout)")
    tr.set_defaults(fn=cmd_trace)

    dbg = sub.add_parser(
        "debug",
        help="runtime forensics: dump the flight-recorder ring "
             "(last-K step/compile/preempt events) from a live server "
             "or the in-process ring",
    )
    dbg.add_argument("action", choices=["dump"])
    dbg.add_argument("--url",
                     help="server base URL (e.g. http://127.0.0.1:8000) "
                          "— fetches GET /debugz; omit to dump the "
                          "in-process ring")
    dbg.add_argument("--last", type=int, default=None,
                     help="only the last K events")
    dbg.add_argument("--out",
                     help="write the JSON document here "
                          "(default: print to stdout)")
    dbg.set_defaults(fn=cmd_debug)

    tu = sub.add_parser(
        "tune",
        help="persistent kernel autotuner: time every registered "
             "kernel variant per shape class (legs moe/lcw/g2, "
             "fwd+grad) and write the winner table as a versioned "
             "artifact for --tune-table; --check validates the "
             "registry + an artifact without timing",
    )
    tu.add_argument("--legs", default="moe,lcw,g2",
                    help="comma-separated tune legs (moe, lcw, g2)")
    tu.add_argument("--out", default="kernels.tune.json",
                    help="winner-table artifact path (atomic write)")
    tu.add_argument("--check", action="store_true",
                    help="no timing: validate registry completeness "
                         "(+ --table artifact schema/winners); exit 1 "
                         "on problems")
    tu.add_argument("--table",
                    help="with --check: an existing artifact to "
                         "validate against the live registry and "
                         "device kind")
    tu.add_argument("--preset", default="full",
                    choices=["full", "smoke"],
                    help="workload shapes: full = bench-leg sized "
                         "(TPU); smoke = tiny CPU-feasible shapes "
                         "(try the flow end to end without a TPU)")
    tu.add_argument("--repeats", type=int, default=3,
                    help="best-of-N timing repeats per candidate")
    tu.set_defaults(fn=cmd_tune)

    lg = sub.add_parser(
        "loadgen",
        help="measurement harness: replay a declarative scenario mix "
             "(chat sessions, RAG prefills, json-mode agents, tool "
             "bursts, batch backfill) at a fixed open-loop offered "
             "load against a live router/server, score per-tier SLO "
             "verdicts from the real /sloz + /metrics scrape, and "
             "optionally run the scenario's scheduled chaos track "
             "(SIGKILL/drain/resume/mid-run rollout); exit 0 = every "
             "tier held its budget, 1 = burning/breached; --check "
             "validates the scenario with no traffic",
    )
    lg.add_argument("--scenario", required=True,
                    help="scenario JSON file, or a built-in name "
                         "(smoke, mixed_peak); docs/loadgen.md has "
                         "the schema")
    lg.add_argument("--url", default="http://127.0.0.1:8000",
                    help="target base URL: a fleet router or a bare "
                         "engine server")
    lg.add_argument("--check", action="store_true",
                    help="validate the scenario (parse, mix weights, "
                         "tier budgets, chaos schedule) and exit — "
                         "no traffic")
    lg.add_argument("--report",
                    help="write the full verdict report JSON here")
    lg.add_argument("--compact-out",
                    help="write the flat lg_* compact row here (the "
                         "shape `obs check-bench --current` gates)")
    lg.add_argument("--duration", type=float,
                    help="override the scenario's duration_s")
    lg.add_argument("--rate", type=float,
                    help="override the scenario's rate_rps")
    lg.add_argument("--seed", type=int,
                    help="override the scenario's seed (same seed = "
                         "same offered timeline + request trace)")
    lg.add_argument("--timeout", type=float, default=30.0,
                    help="per-request timeout (s); a request past it "
                         "is recorded as a transport failure")
    lg.add_argument("--scrape-interval", type=float, default=1.0,
                    help="seconds between /metrics + /sloz + /statz "
                         "snapshots while driving")
    lg.add_argument("--max-inflight", type=int, default=256,
                    help="in-flight cap; arrivals past it are "
                         "recorded as shed (the open loop never "
                         "blocks)")
    lg.add_argument("--chaos-pid", action="append", metavar="ADDR=PID",
                    help="backend address -> OS pid for the chaos "
                         "track's kill action (repeatable)")
    lg.add_argument("--no-chaos", action="store_true",
                    help="ignore the scenario's chaos track (measure "
                         "the same mix undisturbed)")
    lg.set_defaults(fn=cmd_loadgen)

    ob = sub.add_parser(
        "obs",
        help="observability tooling: check-bench gates a compact bench "
             "line against a recorded baseline within declared "
             "tolerances (exit 1 on regression); check-tune diffs two "
             "tune-table artifacts (exit 1 when winners changed); "
             "check-docs gates registered shifu_* metric families "
             "against docs/observability.md (exit 1 on drift); "
             "incident list/show/export inspects a fleet router's "
             "breach bundles; top is a live /statz + /sloz dashboard",
    )
    ob.add_argument("action",
                    choices=["check-bench", "check-tune", "check-docs",
                             "incident", "top"])
    ob.add_argument("sub", nargs="?", default=None,
                    help="incident sub-action: list (default) | show "
                         "| export")
    ob.add_argument("--dir", default="incidents",
                    help="incident: the bundle directory a router's "
                         "--incident-dir wrote (default: incidents)")
    ob.add_argument("--id",
                    help="incident show/export: the bundle id (from "
                         "`obs incident list`)")
    ob.add_argument("--out",
                    help="incident export: output .tar.gz path "
                         "(default: <id>.tar.gz)")
    ob.add_argument("--url", default="http://127.0.0.1:8000",
                    help="top: the router/server base URL to poll")
    ob.add_argument("--interval", type=float, default=2.0,
                    help="top: seconds between dashboard refreshes")
    ob.add_argument("--once", action="store_true",
                    help="top: render one frame and exit (no screen "
                         "clearing — scriptable)")
    ob.add_argument("--loadgen",
                    help="top: a loadgen verdict report (--report "
                         "output) to render as a measurement block, "
                         "re-read every frame")
    ob.add_argument("--baseline",
                    help="baseline record (BENCH_rNN.json driver shape "
                         "or a raw compact line); required for "
                         "check-bench/check-tune")
    ob.add_argument("--current",
                    help="current record to gate (same shapes "
                         "accepted); required for check-bench/"
                         "check-tune")
    ob.add_argument("--doc",
                    help="check-docs: the observability doc to gate "
                         "against (default: docs/observability.md "
                         "next to the package)")
    ob.add_argument("--scale-tolerance", type=float, default=1.0,
                    help="multiply every declared tolerance (loosen "
                         "the whole gate without editing specs)")
    ob.set_defaults(fn=cmd_obs)

    i = sub.add_parser("info", help="environment / device info")
    i.set_defaults(fn=cmd_info)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
