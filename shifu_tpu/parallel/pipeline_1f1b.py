"""1F1B pipeline schedule: backward starts before forward finishes.

The looped pipeline (parallel/pipeline.py) is GPipe-shaped: ALL M
microbatches flow forward, then JAX's AD replays the tick scan in
reverse. Correct and simple — but every stage must keep its boundary
input for every in-flight microbatch until the backward reaches it, an
O(M) stash: (M + P - 1) x (mb, s, d) tensors per stage.

1F1B ("one forward, one backward") turns each microbatch around as soon
as the LAST stage finishes it: stage P-1 computes the head loss and its
cotangent immediately, and the cotangent chases back up the ring while
later microbatches still flow down. A stage then holds at most the
microbatches between its forward and its backward — a 2P-1-deep
CIRCULAR stash, O(P) and independent of M.

JAX's AD cannot express this (backward of a scan runs after the whole
forward), so this module computes the GRADIENTS ITSELF inside one
``shard_map`` scan and exposes the result through ``jax.custom_vjp``:

  * one scan over M + 2P - 2 slots; per slot every stage does one
    (validity-masked) FORWARD microbatch step and one BACKWARD step —
    the classic 1F1B steady state where each device alternates F and B;
  * two ring ``ppermute``s per slot: activations downstream, cotangents
    upstream. Uniform collectives — no stage-dependent control flow;
  * a backward step re-runs its stage from the stashed boundary input
    under ``jax.vjp`` (rematerialisation is inherent: nothing but the
    boundary is ever stored) and accumulates f32 parameter grads;
  * the head (final norm + unembed + CE with z-loss) runs on the last
    stage inside the same slot, producing UNNORMALISED per-row
    ce/z sums and the cotangent of d((ce_sum + z_coef * z_sum)/den)/dh
    — the denominator is just the mask sum, known BEFORE the scan, so
    the head VJP seeds with 1/den and every cotangent in the scan is
    already d(final loss)/d(·) (this is also what lets MoE aux
    cotangents, constants, ride the same backward; the round-6 grouped
    MoE dispatch changes nothing here — its stage body differentiates
    through gathers instead of one-hot einsums, with the identical
    (E, b, C, d) buffers, ep constraints and aux plumbing). The
    custom_vjp backward is then one multiply by the incoming loss
    cotangent;
  * the custom_vjp's residuals ARE the gradients ("self-grad" pattern):
    the forward computes them; the backward is one multiply.

Activation-memory comparison (per stage, boundary tensors of size
A = mb*s*d; in-layer activations are remat'ed in BOTH schedules):

  looped GPipe (pipeline.py):  (M + P - 1) * A
  1F1B (this module):          (2P - 1) * A   (+ the (M, ...) input-
                               cotangent buffer dx, boundary dtype,
                               live on stage 0 only — the same O(M)
                               term the embed backward needs in ANY
                               schedule)

At M = 4P the boundary stash shrinks ~2.6x; for M >> P it approaches
M/(2P).

Scope: the Transformer training path — dense or MoE (router aux
losses accumulate on the forward; their constant pre-normalised
cotangents join the stage VJP on the backward), packed segment_ids
and explicit positions ride as per-microbatch extras. Numerics match
the looped pipeline/sequential scan to float tolerance; grads are
f32. Validated mesh envelope: pp, pp x tp, pp x fsdp, pp x dp x fsdp
and pp x tp x fsdp (tests + the driver dryrun).

SPMD-uniformity notes (the root causes behind the round-2 "cannot
compose with fsdp" limitation, each with its fix in place):

  1. The head runs inside a STAGE-DEPENDENT ``lax.cond``. Any operand
     arriving sharded over an auto (non-pp) mesh axis invites the
     partitioner to insert resharding collectives INSIDE the branch —
     collectives only the last pp stage executes. That is an SPMD
     uniformity violation on every backend (observed concretely as a
     collective-permute rendezvous deadlock on the 8-device CPU mesh:
     the partitioner emitted a cross-fsdp reshard of the targets
     gather, channel pairs spanning all devices, inside branch_1).
     Fixes: the head's small operands (targets, mask, head params) are
     REPLICATED over auto axes before the shard_map (one uniform
     all-gather outside); the loss sums are PER-ROW vectors reduced
     OUTSIDE the shard_map, so no cross-shard reduction ever needs to
     live in the branch.
  2. The two ring ppermutes per slot are data-independent, and at
     pp=2 their source-target pair SETS coincide — XLA assigned both
     the same channel id, so concurrent execution mixes their
     rendezvous. An ``optimization_barrier`` orders the backward
     permute after the forward one, giving every device one total
     order of collectives.
  3. Ambient activation-sharding constraints (the train step's
     ``activation_sharding`` context) landing inside the partial-
     manual body, combined with (1)'s replicated head operands,
     tripped an XLA SPMD partitioner internal CHECK
     ("partition_group_list.num_replica_groups ..." in
     spmd_partitioner_util.cc) on pp x tp x fsdp. The body's auto-axis
     layouts propagate fine from the shard_map inputs, so the adapter
     traces its shard_map under ``no_activation_sharding()``.

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md); there is no reference schedule to match. The
schedule itself is the published 1F1B (PipeDream-flush / Megatron-LM);
this is an original XLA/shard_map expression of it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shifu_tpu.parallel.ctx import shard_map_compat
from shifu_tpu.ops import rms_norm, rope_frequencies


def _build_1f1b(layer_fn, head_fn, mesh: Mesh, axis: str,
                has_aux: bool = False, aux_cot=None):
    """The shard_map program: returns per-stage grads + loss sums.

    ``has_aux``: layer_fn returns ``(h, aux)`` (f32 scalar pytree — the
    MoE router losses). The forward accumulates validity-masked aux
    sums for reporting; the backward feeds ``aux_cot`` (the CONSTANT
    d(final loss)/d(aux sum) — e.g. lb_coef / (n_layers * n_micro)) as
    the aux cotangent of the stage VJP, so router gradients flow in the
    same backward pass as the activation cotangents. This only works
    because cotangents are pre-normalised: the head VJP seeds with
    1/denominator (known before the scan — it is just the mask sum), so
    CE and aux cotangents share one scale and one ppermute.
    """
    n_stages = mesh.shape[axis]
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [((i + 1) % n_stages, i) for i in range(n_stages)]

    def shard_body(
        params_local, head_params, x_local, tgt, msk, extras, per_mb,
        inv_den,
    ):
        stage = jax.lax.axis_index(axis)
        n_micro = x_local.shape[0]
        stash_len = 2 * n_stages - 1
        n_slots = n_micro + 2 * n_stages - 2
        compute_dtype = jax.tree_util.tree_leaves(params_local)[0].dtype
        boundary_dtype = x_local.dtype

        def run_stage(p_loc, h, mbe):
            def body(carry, lp):
                out = layer_fn(lp, carry.astype(compute_dtype), (extras, mbe))
                if has_aux:
                    return out[0], jax.tree_util.tree_map(
                        lambda a: a.astype(jnp.float32), out[1]
                    )
                return out, None

            out, auxes = jax.lax.scan(body, h.astype(compute_dtype), p_loc)
            if has_aux:  # sum over this stage's layers (f32 scalars)
                return out.astype(boundary_dtype), jax.tree_util.tree_map(
                    lambda a: jnp.sum(a), auxes
                )
            return out.astype(boundary_dtype)

        def head_vjp(h, targets, mask):
            """Unnormalised PER-ROW ce/z sums and the cotangent of
            (ce_sum + z_coef * z_sum) / den w.r.t. h and the head
            params (the 1/den seed pre-normalises every downstream
            cotangent — see _build_1f1b docstring; the denominator
            itself is plain data, computed from the mask OUTSIDE the
            scan).

            Per-row (not scalar) sums are load-bearing under partial-
            manual partitioning: a scalar sum over fsdp-sharded rows
            would force the partitioner to insert an all-reduce INSIDE
            this stage-dependent branch — a collective only the last
            pp stage executes, which deadlocks (see module docstring).
            Row vectors keep every op here row-local; the reduction
            happens outside the shard_map, in uniform code."""
            _, vjp, (ce_r, z_r) = jax.vjp(
                lambda hh, hp: _head_objective(
                    head_fn, hh.astype(compute_dtype), hp, targets, mask
                ),
                h, head_params, has_aux=True,
            )
            dh, dhp = vjp(inv_den)
            return (ce_r, z_r), dh.astype(boundary_dtype), dhp

        zero_pgrads = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), params_local
        )
        zero_hgrads = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), head_params
        )
        # The cond's false branch must match head_vjp's dhp dtypes
        # (grads come back in the head params' dtypes).
        zero_hgrads_c = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), head_params
        )

        def mbe_at(m):
            # This microbatch's per-mb extras (packed segment_ids,
            # per-row rope tables) — empty dict when none.
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, m, 0, keepdims=False
                ),
                per_mb,
            )

        def slot(carry, s):
            (h_prev, cot_prev, stash, pg, hg, dx, sums, aux_acc) = carry
            recv_f = jax.lax.ppermute(h_prev, axis, fwd_perm)
            # ORDER the two ring permutes. They are data-independent, and
            # XLA:CPU's thunk executor runs independent collectives
            # concurrently — device threads can then enter the two
            # rendezvous in opposite orders and deadlock (observed on
            # 8-device fsdp-bearing meshes: half the devices blocked on
            # the forward permute's op_id, half on the backward's). The
            # barrier ties the backward permute's operand to the forward
            # permute's result, forcing one schedule on every backend;
            # the tensors are microbatch boundaries, so the serialization
            # cost is noise.
            recv_f, cot_prev = jax.lax.optimization_barrier(
                (recv_f, cot_prev)
            )
            recv_b = jax.lax.ppermute(cot_prev, axis, bwd_perm)

            # ---- forward step: microbatch mF = s - stage ------------
            mF = s - stage
            validF = (mF >= 0) & (mF < n_micro)
            mFc = jnp.clip(mF, 0, n_micro - 1)
            mb_in = jax.lax.dynamic_index_in_dim(
                x_local, mFc, 0, keepdims=False
            )
            h_in = jnp.where(stage == 0, mb_in, recv_f)
            mbeF = mbe_at(mFc)
            if has_aux:
                h_out, auxF = run_stage(params_local, h_in, mbeF)
                aux_acc = jax.tree_util.tree_map(
                    lambda acc, a: acc + jnp.where(validF, a, 0.0),
                    aux_acc, auxF,
                )
            else:
                h_out = run_stage(params_local, h_in, mbeF)
            # Invalid F slots must NOT clobber a live stash entry (the
            # drain phase clips mF onto real microbatch indices whose
            # backward may still be pending).
            old_entry = jax.lax.dynamic_index_in_dim(
                stash, mFc % stash_len, 0, keepdims=False
            )
            stash = jax.lax.dynamic_update_index_in_dim(
                stash,
                jnp.where(validF, h_in, old_entry),
                mFc % stash_len,
                0,
            )

            # ---- head turn-around on the last stage -----------------
            # lax.cond, not masking: the head (vocab-wide logits + VJP)
            # is real FLOPs — running it on every stage would multiply
            # head compute by n_stages. head_vjp contains no collectives,
            # so a stage-dependent branch is safe; only the ppermutes
            # must stay uniform.
            tF = jax.lax.dynamic_index_in_dim(tgt, mFc, 0, keepdims=False)
            kF = jax.lax.dynamic_index_in_dim(msk, mFc, 0, keepdims=False)
            at_head = (stage == n_stages - 1) & validF

            mb_rows = x_local.shape[1]

            def do_head(_):
                return head_vjp(h_out, tF, kF)

            def skip_head(_):
                z = jnp.zeros((mb_rows,), jnp.float32)
                return (z, z), jnp.zeros_like(h_out), zero_hgrads_c

            (ce_r, z_r), head_cot, dhp = jax.lax.cond(
                at_head, do_head, skip_head, None
            )
            sums = (sums[0] + ce_r, sums[1] + z_r)
            hg = jax.tree_util.tree_map(
                lambda acc, g: acc + g.astype(jnp.float32), hg, dhp
            )

            # ---- backward step: microbatch mB -----------------------
            mB = s - (2 * n_stages - 2 - stage)
            validB = (mB >= 0) & (mB < n_micro)
            mBc = jnp.clip(mB, 0, n_micro - 1)
            h_in_b = jax.lax.dynamic_index_in_dim(
                stash, mBc % stash_len, 0, keepdims=False
            )
            cot_in = jnp.where(stage == n_stages - 1, head_cot, recv_b)
            mbeB = mbe_at(mBc)
            _, stage_vjp = jax.vjp(
                lambda pl, hh: run_stage(pl, hh, mbeB),
                params_local, h_in_b,
            )
            if has_aux:
                # The aux sums' cotangent is a CONSTANT (coef / (L*M),
                # pre-normalised like everything else) — zeroed on
                # invalid slots so drain-phase re-runs of clipped
                # microbatches add nothing.
                acm = jax.tree_util.tree_map(
                    lambda c: jnp.where(validB, jnp.float32(c), 0.0),
                    aux_cot,
                )
                dp, dh_in = stage_vjp(
                    (cot_in.astype(boundary_dtype), acm)
                )
            else:
                dp, dh_in = stage_vjp(cot_in.astype(boundary_dtype))
            pg = jax.tree_util.tree_map(
                lambda acc, g: acc
                + jnp.where(validB, g.astype(jnp.float32), 0.0),
                pg,
                dp,
            )
            # dx holds each microbatch's input cotangent ONCE (no
            # accumulation), so the boundary dtype loses nothing and
            # halves the buffer vs f32.
            dx = jax.lax.dynamic_update_index_in_dim(
                dx,
                jnp.where(
                    validB & (stage == 0),
                    dh_in.astype(boundary_dtype),
                    jax.lax.dynamic_index_in_dim(dx, mBc, 0, keepdims=False),
                ),
                mBc,
                0,
            )
            return (h_out, dh_in, stash, pg, hg, dx, sums, aux_acc), None

        mb_shape = x_local[0]
        zrow = jnp.zeros((x_local.shape[1],), jnp.float32)
        aux0 = None
        if has_aux:
            aux0 = jax.tree_util.tree_map(
                lambda _: jnp.zeros((), jnp.float32), aux_cot
            )
        init = (
            jnp.zeros_like(mb_shape),
            jnp.zeros_like(mb_shape),
            jnp.zeros((stash_len, *mb_shape.shape), boundary_dtype),
            zero_pgrads,
            zero_hgrads,
            jnp.zeros(x_local.shape, boundary_dtype),
            (zrow, zrow),
            aux0,
        )
        (_, _, _, pg, hg, dx, sums, aux_acc), _ = jax.lax.scan(
            slot, init, jnp.arange(n_slots)
        )
        # Per-stage leading axis on everything (out_specs pins pp there):
        # block grads reassemble into the stacked layer axis; head grads
        # and sums add up across stages (only the last stage's are
        # nonzero); dx is real only on stage 0; aux sums add over stages.
        lead = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
        return lead(pg), lead(hg), lead(dx), lead(sums), lead(aux_acc)

    return jax.jit(
        shard_map_compat(
            shard_body,
            mesh=mesh,
            in_specs=(P(axis), P(), P(), P(), P(), P(), P(), P()),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            axis_names={axis},
            check_vma=False,
        )
    )


def _head_objective(head_fn, h, head_params, targets, mask):
    """(ce_sum + z_coef*z_sum) as the differentiated scalar; PER-ROW
    sums as aux (row-local — see head_vjp for why)."""
    ce_r, z_r, z_coef = head_fn(h, head_params, targets, mask)
    return jnp.sum(ce_r) + z_coef * jnp.sum(z_r), (ce_r, z_r)


class Pipelined1F1BModel:
    """Adapter: a dense Transformer whose ``loss`` runs the 1F1B
    schedule with self-computed gradients (module docstring).

    Quacks like the wrapped model for the train stack, exactly like
    ``parallel.pipeline.PipelinedModel``:

        pm = Pipelined1F1BModel(model, mesh=mesh, microbatches=8)
        state = create_sharded_state(pm, opt, rng, mesh)
        step = make_train_step(pm, opt, mesh)

    ``loss`` is differentiable (custom_vjp): its forward computes loss
    AND gradients on the 1F1B schedule; value_and_grad's backward just
    scales them. MoE models ride the same schedule: router aux losses
    accumulate on the forward and their (constant, pre-normalised)
    cotangents join the stage VJP on the backward. Packed segment_ids
    and explicit positions ship as per-microbatch extras.
    """

    def __init__(self, model, *, mesh: Mesh, microbatches: int,
                 axis: str = "pp"):
        cfg = model.cfg
        self.inner = model
        self.cfg = cfg
        self.mesh = mesh
        self.microbatches = microbatches
        self.axis = axis
        has_aux = bool(getattr(cfg, "n_experts", 0))

        def layer_fn(layer_p, h, extras):
            shared, mbe = extras
            sin = mbe.get("sin", shared[0] if shared else None)
            cos = mbe.get("cos", shared[1] if shared else None)
            seg = mbe.get("seg")
            out, _, aux = model._block(layer_p, h, sin, cos, seg, None, None)
            return (out, aux) if has_aux else out

        z_coef = float(cfg.z_loss)
        # d(final loss)/d(per-stage aux sums): the aggregate aux is the
        # layer-and-microbatch MEAN (matching PipelinedModel /
        # model.loss), so each summed term's cotangent is coef / (L*M).
        # "dropped" is reporting-only — zero cotangent.
        aux_cot = None
        if has_aux:
            denom_lm = float(cfg.n_layers * microbatches)
            aux_cot = {
                "lb": float(cfg.moe_lb_coef) / denom_lm,
                "rz": float(cfg.moe_rz_coef) / denom_lm,
                "dropped": 0.0,
            }

        def head_fn(h, head_params, targets, mask):
            """Unnormalised PER-ROW CE/z sums for ONE microbatch (f32).
            Row-local by construction (reduce over seq only) so the
            partitioner never needs a cross-shard reduction inside the
            stage-dependent head branch."""
            h = rms_norm(
                h, head_params["final_norm"].astype(h.dtype),
                eps=cfg.norm_eps,
            )
            w = head_params["unembed"].astype(h.dtype)
            logits = jnp.einsum("bsd,dv->bsv", h, w).astype(jnp.float32)
            log_z = jax.nn.logsumexp(logits, axis=-1)
            label_logits = jnp.take_along_axis(
                logits, targets[..., None], axis=-1
            ).squeeze(-1)
            ce = log_z - label_logits
            z = jnp.square(log_z)
            w_ = mask.astype(jnp.float32)
            return (
                jnp.sum(ce * w_, axis=-1),
                jnp.sum(z * w_, axis=-1),
                jnp.float32(z_coef),
            )

        self._fn = _build_1f1b(
            layer_fn, head_fn, mesh, axis, has_aux=has_aux,
            aux_cot=aux_cot,
        )
        self._model = model
        self._has_aux = has_aux
        self._aux_cot = aux_cot

        # --- the differentiable pipelined loss -----------------------
        @jax.custom_vjp
        def pipelined_loss(params, batch):
            loss, aux, _grads = _forward(params, batch)
            return loss, aux

        def _forward(params, batch):
            model_ = self._model
            cfg_ = self.cfg
            tokens = batch["tokens"]
            b, s_full = tokens.shape
            M = self.microbatches
            if b % M:
                raise ValueError(
                    f"batch {b} not divisible into {M} microbatches"
                )
            inp = tokens[:, :-1]
            tgt = tokens[:, 1:]
            msk = batch.get("mask")
            msk = (
                jnp.ones_like(tgt, jnp.float32)
                if msk is None
                else msk[:, 1:].astype(jnp.float32)
            )
            s = s_full - 1

            p = model_.policy.cast_to_compute(params)
            h = jnp.take(p["embed"], inp, axis=0)
            # XLA:CPU partitioner workaround (see pipeline.py): keep the
            # shard_map boundary f32 there; TPU keeps the narrow dtype.
            if (
                jax.default_backend() == "cpu"
                and h.dtype == jnp.bfloat16
            ):
                h = h.astype(jnp.float32)
            mb = b // M
            d = h.shape[-1]
            # Rope tables + packed-segment extras. Shared tables (no
            # explicit positions) replicate to every slot; per-row
            # tables and segment_ids ship per-microbatch, indexed by
            # the slot's mF/mB inside the scan.
            positions = batch.get("positions")
            positions = (
                jnp.arange(s) if positions is None else positions[:, :-1]
            )
            sin, cos = rope_frequencies(
                cfg_.resolved_head_dim, positions, theta=cfg_.rope_theta,
                scaling=cfg_.rope_scaling,
            )
            per_mb = {}
            shared = (sin, cos)
            if sin.ndim == 3:  # (b, s, hd/2): per-row positions
                per_mb["sin"] = sin.reshape(M, mb, *sin.shape[1:])
                per_mb["cos"] = cos.reshape(M, mb, *cos.shape[1:])
                shared = None
            seg = batch.get("segment_ids")
            if seg is not None:
                per_mb["seg"] = seg[:, :-1].reshape(M, mb, s)
            head_params = {
                "final_norm": p["final_norm"],
                "unembed": (
                    p["embed"].T if cfg_.tie_embeddings else p["unembed"]
                ),
            }

            # Replicate the head branch's operands over the AUTO mesh
            # axes (fsdp/dp/tp) OUTSIDE the shard_map. The head runs
            # inside a stage-dependent lax.cond; if any of its operands
            # arrive sharded over an auto axis, the partitioner inserts
            # resharding collectives INSIDE the branch — collectives
            # only the last pp stage executes, which is an SPMD
            # uniformity violation (observed as a collective-permute
            # rendezvous deadlock on the 8-device CPU mesh; on TPU the
            # same non-uniform collective would hang the program).
            # Targets/mask are int32/f32 (b, s) and the head params are
            # the final norm + unembed — replicating them here is one
            # uniform all-gather, after which every op in the branch is
            # local. Activations (h) stay sharded: the head's row-local
            # math composes with them without collectives once the
            # row-sum outputs are vectors (see head_vjp).
            if self.mesh.size > 1:
                from jax.sharding import NamedSharding

                rep = NamedSharding(self.mesh, P())
                head_params = jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(a, rep),
                    head_params,
                )
                tgt = jax.lax.with_sharding_constraint(tgt, rep)
                msk = jax.lax.with_sharding_constraint(msk, rep)
            # The shard_map body manages its own sharding (pp manually,
            # auto axes by propagation from the inputs). Ambient
            # per-activation constraints from the train step's
            # activation_sharding context would land INSIDE the body
            # and, combined with the replicated head operands above,
            # trip an XLA SPMD partitioner internal check on
            # pp x tp x fsdp meshes — suppress them for this trace.
            from shifu_tpu.parallel.ctx import no_activation_sharding

            # The denominator is data, not model output — computing it
            # UP FRONT lets the head VJP seed with 1/den, so every
            # cotangent in the scan (CE and MoE aux alike) is already
            # d(final loss)/d(·) and the custom_vjp backward is one
            # multiply by the incoming loss cotangent.
            den = jnp.maximum(jnp.sum(msk), 1.0)
            inv_den = (1.0 / den).astype(jnp.float32)
            with no_activation_sharding():
                pg, hg, dx, sums, aux_acc = self._fn(
                    p["blocks"],
                    head_params,
                    h.reshape(M, mb, s, d),
                    tgt.reshape(M, mb, s),
                    msk.reshape(M, mb, s),
                    shared,
                    per_mb,
                    inv_den,
                )
            # Reassemble: block grads carry the stacked layer axis back
            # (the per-stage leading axis IS the pp sharding of layers);
            # head grads / sums add over stages; dx is stage 0's.
            n_l = jax.tree_util.tree_leaves(p["blocks"])[0].shape[0]
            pg = jax.tree_util.tree_map(
                lambda g: g.reshape(n_l, *g.shape[2:]), pg
            )
            hg = jax.tree_util.tree_map(lambda g: g.sum(0), hg)
            dx = dx[0].reshape(b, s, d)
            ce_s = sums[0].sum()
            z_s = sums[1].sum()
            loss = (ce_s + float(cfg_.z_loss) * z_s) / den
            aux = {"ce": ce_s / den, "z": z_s / den, "denominator": den}
            if self._has_aux:
                # Layer-and-microbatch mean, matching PipelinedModel /
                # model.loss semantics.
                n_layers = cfg_.n_layers
                moe_aux = jax.tree_util.tree_map(
                    lambda a: a.sum() / (n_layers * M), aux_acc
                )
                loss = (
                    loss
                    + float(cfg_.moe_lb_coef) * moe_aux["lb"]
                    + float(cfg_.moe_rz_coef) * moe_aux["rz"]
                )
                aux.update({f"moe_{k}": v for k, v in moe_aux.items()})
            return loss, aux, (pg, hg, dx, inp)

        def fwd(params, batch):
            loss, aux, grads = _forward(params, batch)
            return (loss, aux), (params, grads)

        def bwd(res, g):
            params, (pg, hg, dx, inp) = res
            # aux is reporting-only; its cotangent (g[1]) is dropped.
            # Grads are already d(loss)/d(·) — the 1/den normalisation
            # rode the head VJP's seed — so the only scale left is the
            # incoming loss cotangent itself.
            scale = g[0]
            # Embed grad: transpose of the gather. Expressed as a
            # one-hot matmul rather than a scatter-add: the SPMD
            # partitioner handles a dot over a (vocab->tp, embed->fsdp)
            # sharded output cleanly where the equivalent scatter
            # crashes the XLA:CPU partitioner on pp+tp+fsdp meshes, and
            # on TPU the dot rides the MXU (~1% of a train step at 1B).
            # CHUNKED over microbatches: a whole-batch one-hot would be
            # (b*s, V) — bigger than everything the O(P) schedule saves.
            v = params["embed"].shape[0]
            d_model = dx.shape[-1]
            dx_m = dx.reshape(self.microbatches, -1, d_model)
            inp_m = inp.reshape(self.microbatches, -1)

            def acc_embed(acc, mi):
                dxc, ic = mi
                onehot = jax.nn.one_hot(ic, v, dtype=jnp.bfloat16)
                return acc + jnp.einsum(
                    "nv,nd->vd", onehot, dxc.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                ), None

            d_embed, _ = jax.lax.scan(
                acc_embed,
                jnp.zeros((v, d_model), jnp.float32),
                (dx_m, inp_m),
            )
            out = {
                "blocks": jax.tree_util.tree_map(
                    lambda gq, pp_: (gq * scale).astype(pp_.dtype),
                    pg,
                    params["blocks"],
                ),
                "final_norm": (hg["final_norm"] * scale).astype(
                    params["final_norm"].dtype
                ),
            }
            if self.cfg.tie_embeddings:
                d_embed = d_embed + hg["unembed"].T
            else:
                out["unembed"] = (hg["unembed"] * scale).astype(
                    params["unembed"].dtype
                )
            out["embed"] = (d_embed * scale).astype(params["embed"].dtype)
            return out, None

        pipelined_loss.defvjp(fwd, bwd)
        self._pipelined_loss = pipelined_loss
        self._forward_impl = _forward

    def loss(self, params, batch):
        # ONE pipelined forward: the custom_vjp's primal is (loss, aux).
        return self._pipelined_loss(params, batch)

    def specs(self):
        return self.inner.specs()

    def axes(self):
        return self.inner.axes()

    def init(self, rng):
        return self.inner.init(rng)
