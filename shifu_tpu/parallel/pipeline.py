"""Looped pipeline parallelism over the ``pp`` mesh axis.

The stacked-layers models already *shard* their layer axis over pp, but a
plain sharded scan serialises: stage p+1's first layer waits for stage p's
last layer for the whole batch. This module adds the real pipelined
schedule (GPipe-style) as a drop-in apply:

  * the mesh's ``pp`` axis is made *manual* via ``jax.shard_map`` (other
    axes — dp/fsdp/tp — stay automatic, so tensor/data sharding inside a
    stage keeps working);
  * each stage holds L/P contiguous layers and loops T = M + P - 1 ticks;
    at every tick it receives its predecessor's activation via a ring
    ``ppermute``, runs its layer slice, and passes on — after the P-1-tick
    fill, all P stages compute different microbatches concurrently;
  * the backward schedule comes from AD: ppermute's transpose is the
    reverse permute, so differentiating the tick scan yields the reverse
    pipeline automatically (rematerialise the stage body to keep the
    T-tick activation buffer small).

Cost model: bubble fraction = (P-1)/(M+P-1) — use M >= 4P microbatches.
Activation traffic per tick is one (mb, s, d) block over ICI, overlapped
with the next tick's compute by XLA's async collectives.

MoE legs (round 6): the block's expert FFN now defaults to the GROUPED
sorted dispatch (ops/moe.py) — the stage body's layer_fn carries it
unchanged, since the grouped path keeps the same (E, b, C, d) buffer
layout and ep activation constraints as the einsum oracle; the router
aux losses ride the existing ``has_aux`` plumbing untouched.

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md); there is no reference pipeline engine to match.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shifu_tpu.parallel.ctx import shard_map_compat


def pipeline_apply(
    layer_fn: Callable,
    stacked_params: Any,
    x: jax.Array,
    extras: Any = None,
    mb_extras: Any = None,
    *,
    mesh: Mesh,
    axis: str = "pp",
    remat_stage: bool = True,
    has_aux: bool = False,
):
    """Run microbatches through pp-sharded stacked layers, pipelined.

    Args:
      layer_fn: ``(layer_params, h, extras) -> h`` — ONE layer;  each
        stage scans it over its local slice of the stacked axis. With
        ``has_aux``, returns ``(h, aux)`` where aux is a pytree of f32
        SCALARS (e.g. MoE load-balance losses); pipeline_apply returns
        their mean over all (layer, microbatch) applications.
      stacked_params: pytree whose leaves have a leading layer axis of
        extent L with ``L % pp == 0``. May carry any dp/fsdp/tp sharding
        on later axes (those stay automatic).
      x: (M, mb, ...) microbatched inputs; M microbatches flow through
        the pipeline. Batch/seq axes may be sharded over other mesh axes.
      extras: replicated-per-stage constants (e.g. rope sin/cos tables),
        passed to every layer invocation.
      mb_extras: PER-MICROBATCH constants — a pytree with a leading M
        axis (e.g. packed segment_ids, explicit positions). Each stage
        indexes its CURRENT microbatch (t - stage) out of the replicated
        tree, so per-microbatch data never rides the ring. When given,
        ``layer_fn`` receives ``(extras, current_mb_extras)`` as its
        third argument; with mb_extras=None the contract is unchanged
        (plain ``extras``).
      mesh: mesh containing ``axis``.
      remat_stage: rematerialise each stage body in the backward pass.
      has_aux: layer_fn returns (h, aux-scalars); see above.

    Returns:
      (M, mb, ...) outputs — the result of applying all L layers to every
      microbatch, numerically equal to a sequential scan over layers.
      With ``has_aux``: ``(outputs, aux)`` where aux is the layer- and
      microbatch-mean of layer_fn's aux pytree.
    """
    n_stages = mesh.shape[axis]
    if n_stages == 1:
        # Degenerate pipeline: sequential scan, same contract (including
        # per-layer rematerialisation when requested).
        def one(mb, mbe):
            eff = extras if mb_extras is None else (extras, mbe)
            step = lambda h, lp: layer_fn(lp, h, eff)
            if remat_stage:
                step = jax.checkpoint(step)

            def body(h, lp):
                out = step(h, lp)
                return (out[0], out[1]) if has_aux else (out, None)

            out, auxes = jax.lax.scan(body, mb, stacked_params)
            if has_aux:  # mean over this microbatch's layers
                return out, jax.tree_util.tree_map(jnp.mean, auxes)
            return out

        mapped = (
            jax.lax.map(lambda mb: one(mb, None), x)
            if mb_extras is None
            else jax.lax.map(lambda args: one(*args), (x, mb_extras))
        )
        if has_aux:
            out, auxes = mapped
            return out, jax.tree_util.tree_map(jnp.mean, auxes)
        return mapped

    # XLA:CPU partitioner workaround: transposing a dtype convert on an
    # array that crosses the partial-manual shard_map boundary crashes the
    # CPU SPMD partitioner ("Invalid binary instruction opcode copy").
    # Keep the boundary f32 there and convert inside the manual region
    # (where no resharding happens). TPU keeps the native narrow boundary.
    compute_dtype = x.dtype
    f32_boundary = (
        jax.default_backend() == "cpu" and compute_dtype == jnp.bfloat16
    )
    if f32_boundary:
        x = x.astype(jnp.float32)

    fn = _pipeline_fn(layer_fn, mesh, axis, remat_stage, has_aux)
    staged = fn(stacked_params, x, extras, mb_extras)
    if has_aux:
        staged, aux_stages = staged
        # Per-stage aux sums (leading pp axis, one entry per stage) add
        # up to the total over all (layer, microbatch) applications;
        # normalise to the mean. Summing OUTSIDE the manual region
        # avoids an in-region psum (and its XLA:CPU partitioner issues).
        n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        n_micro = x.shape[0]
        aux = jax.tree_util.tree_map(
            lambda a: jnp.sum(a, axis=0) / (n_layers * n_micro), aux_stages
        )
    out = staged[n_stages - 1]
    out = out.astype(compute_dtype) if f32_boundary else out
    return (out, aux) if has_aux else out


def _pipeline_fn(
    layer_fn, mesh: Mesh, axis: str, remat_stage: bool, has_aux: bool
):
    """The jitted pipelined program, cached per (layer_fn, mesh, axis).

    Everything shape-dependent (microbatch count, tick count, dtypes) is
    derived at trace time from the arguments, so eager callers hit jit's
    own shape-keyed cache instead of recompiling per call. The cache
    lives as an attribute ON ``layer_fn`` itself: the resulting reference
    cycle (fn -> cache -> jitted program -> closure -> fn) is ordinary
    gc-collectable garbage once the owner drops the closure, so compiled
    executables die with the loss function that created them. (A
    WeakKeyDictionary would NOT achieve this: its strong value reference
    back to the key would make entries immortal.)
    """
    cache = getattr(layer_fn, "__shifu_pipeline_cache__", None)
    if cache is None:
        try:
            cache = {}
            layer_fn.__shifu_pipeline_cache__ = cache
        except AttributeError:
            # Non-attributable callable (bound method, __slots__ object):
            # fall back to a small bounded LRU module cache — still cached
            # (no silent per-call recompiles), just capped instead of
            # owner-scoped. Hits refresh recency so active callables are
            # not evicted by rotation.
            cache = _FALLBACK_CACHE.pop(layer_fn, None)
            if cache is None:
                cache = {}
            _FALLBACK_CACHE[layer_fn] = cache  # (re)insert most-recent
            while len(_FALLBACK_CACHE) > 8:
                _FALLBACK_CACHE.pop(next(iter(_FALLBACK_CACHE)))
    key = (mesh, axis, remat_stage, has_aux)
    if key not in cache:
        cache[key] = _build_pipeline_fn(
            layer_fn, mesh, axis, remat_stage, has_aux
        )
    return cache[key]


_FALLBACK_CACHE: dict = {}


def _build_pipeline_fn(
    layer_fn, mesh: Mesh, axis: str, remat_stage: bool, has_aux: bool
):
    n_stages = mesh.shape[axis]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def shard_body(params_local, x_local, extras_local, mb_extras_local):
        stage = jax.lax.axis_index(axis)
        n_micro = x_local.shape[0]
        n_ticks = n_micro + n_stages - 1
        # Compute in the params' dtype; the boundary (x_local) may be
        # wider (the f32 CPU workaround above).
        compute_dtype = jax.tree_util.tree_leaves(params_local)[0].dtype
        boundary_dtype = x_local.dtype

        def run_stage(h, mbe):
            # Contract: layer_fn sees plain ``extras`` when no
            # per-microbatch data exists, else the pair (extras, mbe).
            eff = (
                extras_local
                if mb_extras_local is None
                else (extras_local, mbe)
            )

            def body(carry, lp):
                out = layer_fn(lp, carry, eff)
                return (out[0], out[1]) if has_aux else (out, None)

            out, auxes = jax.lax.scan(
                body, h.astype(compute_dtype), params_local
            )
            # Aux: SUM over this stage's local layers (normalised to a
            # mean once, outside the manual region).
            stage_aux = (
                jax.tree_util.tree_map(
                    lambda a: jnp.sum(a.astype(jnp.float32)), auxes
                )
                if has_aux
                else None
            )
            return out.astype(boundary_dtype), stage_aux

        if remat_stage:
            run_stage = jax.checkpoint(run_stage)

        def tick(carry, t):
            prev_out, out_buf, aux_acc = carry
            recv = jax.lax.ppermute(prev_out, axis, perm)
            mb = jax.lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            h_in = jnp.where(stage == 0, mb, recv)
            # Stage p processes microbatch (t - p) at tick t; index its
            # per-microbatch constants out of the replicated tree.
            my_mb = jnp.clip(t - stage, 0, n_micro - 1)
            mbe = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, my_mb, 0, keepdims=False
                ),
                mb_extras_local,
            )
            h_out, stage_aux = run_stage(h_in, mbe)
            if has_aux:
                # Fill/drain ticks run on a clipped (garbage) microbatch;
                # only real ones count toward the aux sums.
                real = (t >= stage) & (t - stage <= n_micro - 1)
                aux_acc = jax.tree_util.tree_map(
                    lambda acc, a: acc + jnp.where(real, a, 0.0),
                    aux_acc,
                    stage_aux,
                )
            # The last stage finishes microbatch (t - (P-1)) at tick t.
            emit = (stage == n_stages - 1) & (t >= n_stages - 1)
            idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, idx, 0, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(emit, h_out, cur), idx, 0
            )
            return (h_out, out_buf, aux_acc), None

        aux0 = None
        if has_aux:
            mbe0 = jax.tree_util.tree_map(
                lambda a: a[0], mb_extras_local
            )
            aux_shapes = jax.eval_shape(run_stage, x_local[0], mbe0)[1]
            aux0 = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), aux_shapes
            )
        init = (jnp.zeros_like(x_local[0]), jnp.zeros_like(x_local), aux0)
        (_, out_buf, aux_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        # Only the last stage holds real outputs. Return with a leading
        # per-stage axis (out_specs puts pp there) and let the caller
        # slice stage P-1 — a plain resharding outside the manual region,
        # cheaper than an in-region psum broadcast (and it sidesteps an
        # XLA:CPU partitioner crash on bf16 psum of a replicated operand).
        # Aux sums get the same per-stage axis; the caller adds them up.
        if has_aux:
            return out_buf[None], jax.tree_util.tree_map(
                lambda a: a[None], aux_acc
            )
        return out_buf[None]

    # Specs are pytree prefixes: one spec covers each whole argument tree.
    return jax.jit(
        shard_map_compat(
            shard_body,
            mesh=mesh,
            in_specs=(P(axis), P(), P(), P()),
            out_specs=P(axis),  # leading per-stage axis
            axis_names={axis},
            check_vma=False,
        )
    )


def pipeline_loss_fn(
    model,
    *,
    mesh: Mesh,
    microbatches: int,
    axis: str = "pp",
    remat_stage: Optional[bool] = None,
):
    """Pipelined next-token loss for a stacked-layers Transformer.

    Returns ``loss_fn(params, batch) -> (loss, aux)`` — same contract as
    ``model.loss`` so it plugs straight into ``make_train_step``'s
    value_and_grad. The implementation is ``model.loss`` itself with the
    block stack swapped for :func:`pipeline_apply` via the model's
    ``blocks_fn`` hook — embed/rope/norms/unembed/CE (and their
    activation-sharding anchors) have exactly one implementation. Batch
    leaves are (b, s); rows are split into ``microbatches`` along the
    batch axis (b % microbatches == 0).

    ``remat_stage`` defaults to the model config's ``remat``. Supports the
    Transformer training path (no KV cache), dense or MoE — MoE blocks'
    expert buffers keep their ep sharding inside a stage (constrain is
    partial-manual aware), and the router aux losses ride pipeline_apply's
    ``has_aux`` path back to ``model.loss``.
    """
    cfg = model.cfg
    has_aux = bool(getattr(cfg, "n_experts", 0))
    if remat_stage is None:
        remat_stage = getattr(cfg, "remat", True)

    def layer_fn(layer_p, h, extras):
        # blocks_fn always passes mb_extras (possibly an empty dict), so
        # the contract is uniformly ((sin?, cos?) shared, mbe dict).
        shared, mbe = extras
        sin = mbe.get("sin", shared[0] if shared else None)
        cos = mbe.get("cos", shared[1] if shared else None)
        seg = mbe.get("seg")
        out, _, aux = model._block(layer_p, h, sin, cos, seg, None, None)
        return (out, aux) if has_aux else out

    def blocks_fn(stacked_blocks, h, sin, cos, segment_ids):
        b, s, d = h.shape
        if b % microbatches:
            raise ValueError(
                f"batch {b} not divisible into {microbatches} microbatches"
            )
        mb = b // microbatches
        h = h.reshape(microbatches, mb, s, d)
        # Per-ROW rope tables (explicit positions) and packed segments
        # vary per microbatch: ship them via mb_extras so each stage
        # indexes its current microbatch's slice. Shared rope tables
        # (positions=None -> (s, hd/2)) stay replicated extras.
        per_mb = {}
        shared = (sin, cos)
        if sin.ndim == 3:  # (b, s, hd/2): per-row positions
            per_mb["sin"] = sin.reshape(microbatches, mb, *sin.shape[1:])
            per_mb["cos"] = cos.reshape(microbatches, mb, *cos.shape[1:])
            shared = None
        if segment_ids is not None:
            per_mb["seg"] = segment_ids.reshape(microbatches, mb, s)
        # Always pass the (possibly empty) dict: zero extra pytree leaves,
        # and layer_fn gets one uniform contract to unpack.
        out = pipeline_apply(
            layer_fn,
            stacked_blocks,
            h,
            shared,
            per_mb,
            mesh=mesh,
            axis=axis,
            remat_stage=remat_stage,
            has_aux=has_aux,
        )
        if has_aux:
            h, aux = out
            return h.reshape(b, s, d), aux
        return out.reshape(b, s, d)

    def loss_fn(params, batch):
        return model.loss(params, batch, blocks_fn=blocks_fn)

    return loss_fn


class PipelinedModel:
    """Adapter: a model whose ``loss`` runs the looped-pipeline schedule.

    Quacks like the wrapped model for the train stack (specs/axes/init for
    sharded state creation and the decay mask) while ``loss`` goes through
    :func:`pipeline_loss_fn` — so ``create_sharded_state`` and
    ``make_train_step`` work unchanged:

        pm = PipelinedModel(model, mesh=mesh, microbatches=8)
        state = create_sharded_state(pm, opt, rng, mesh)
        step = make_train_step(pm, opt, mesh)
    """

    def __init__(self, model, *, mesh, microbatches, axis: str = "pp"):
        self.inner = model
        self.cfg = model.cfg
        self.loss = pipeline_loss_fn(
            model, mesh=mesh, microbatches=microbatches, axis=axis
        )

    def specs(self):
        return self.inner.specs()

    def axes(self):
        return self.inner.axes()

    def init(self, rng):
        return self.inner.init(rng)
