from shifu_tpu.parallel.ctx import activation_sharding, constrain
from shifu_tpu.parallel.mesh import MESH_AXES, MeshPlan
from shifu_tpu.parallel.sharding import (
    DEFAULT_RULES,
    abstract_params,
    batch_spec,
    init_sharded,
    param_shardings,
    shard_params,
    param_specs_tree,
    shard_batch,
    spec_for,
)

__all__ = [
    "activation_sharding",
    "constrain",
    "MESH_AXES",
    "MeshPlan",
    "DEFAULT_RULES",
    "abstract_params",
    "batch_spec",
    "init_sharded",
    "param_shardings",
    "shard_params",
    "param_specs_tree",
    "shard_batch",
    "spec_for",
]
from shifu_tpu.parallel.pipeline import (  # noqa: E402
    PipelinedModel,
    pipeline_apply,
    pipeline_loss_fn,
)

from shifu_tpu.parallel.pipeline_1f1b import (  # noqa: E402
    Pipelined1F1BModel,
)

__all__ += [
    "PipelinedModel",
    "Pipelined1F1BModel",
    "pipeline_apply",
    "pipeline_loss_fn",
]
from shifu_tpu.parallel.distributed import (  # noqa: E402
    HybridMeshPlan,
    initialize,
    is_coordinator,
    shard_host_batch,
)

__all__ += ["HybridMeshPlan", "initialize", "is_coordinator", "shard_host_batch"]
