"""Device mesh planning.

One mesh, six named axes, fixed order:

  ("dp", "fsdp", "ep", "pp", "sp", "tp")

  * dp   — pure data parallelism (params replicated)
  * fsdp — data parallelism with params/optimizer sharded (ZeRO-3 style;
           XLA turns the annotations into all-gather / reduce-scatter)
  * ep   — expert parallelism for MoE layers
  * pp   — pipeline stages (layers axis)
  * sp   — sequence/context parallelism (ring attention rides this axis)
  * tp   — tensor parallelism (heads / mlp / vocab)

Axis order is chosen so the innermost (fastest-varying, best ICI locality
under ``create_device_mesh``) axes are tp and sp — the ones with per-layer
collectives on the critical path. dp/fsdp gradient reductions happen once
per step and tolerate the outer placement.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

import jax
from jax.sharding import Mesh

MESH_AXES = ("dp", "fsdp", "ep", "pp", "sp", "tp")


def device_array(shape, devices) -> np.ndarray:
    """Devices arranged for a mesh of ``shape``: topology-aware on real
    multi-chip TPU (ICI-neighbour placement via create_device_mesh), plain
    reshape elsewhere. Shared by MeshPlan and HybridMeshPlan builds."""
    if len(devices) > 1 and devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        return mesh_utils.create_device_mesh(shape, devices=devices)
    return np.asarray(devices).reshape(shape)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple:
        return tuple(getattr(self, a) for a in MESH_AXES)

    @property
    def n_devices(self) -> int:
        return math.prod(self.shape)

    def build(self, devices=None) -> Mesh:
        if devices is None:
            devices = jax.devices()
        if self.n_devices != len(devices):
            raise ValueError(
                f"MeshPlan {self.shape} needs {self.n_devices} devices, "
                f"got {len(devices)}"
            )
        return Mesh(device_array(self.shape, devices), MESH_AXES)

    @classmethod
    def single_device(cls) -> "MeshPlan":
        return cls()

    @classmethod
    def fsdp_only(cls, n: int) -> "MeshPlan":
        return cls(fsdp=n)

    @classmethod
    def serving(cls, tp: int = 1, ep: int = 1) -> "MeshPlan":
        """One serving replica's sub-mesh: tp shards heads/mlp/vocab
        (and the KV cache's kv-heads axis), ep shards MoE expert
        weights and the (E, b, C, d) dispatch buffers so MoE decode
        holds 1/ep of the expert weights per chip instead of a full
        replica. dp replication happens ABOVE this (one such mesh per
        replica — infer.replica.build_replicated); every other axis is
        1 so the standard sharding rules apply unchanged."""
        return cls(ep=ep, tp=tp)
