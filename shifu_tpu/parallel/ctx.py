"""Activation-sharding context.

Parameters get shardings from their ParamSpec axes; *activations* get theirs
from ``constrain(x, logical_axes)`` calls inside model code. The mesh+rules
pair is carried in a context variable so model code stays device-free: with
no context active, ``constrain`` is the identity.

The training step enters the context around the loss (make_train_step), so
constraints are recorded during jit tracing. Beyond steering XLA toward the
intended layout (avoid accidental all-gathers of full activations), explicit
anchors also sidestep partitioner corner cases observed on XLA:CPU where
composite gather-backward programs under multi-axis sharding miscompiled to
NaN (see tests/test_sharding.py::test_sharded_train_step_*).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Mapping, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding

from shifu_tpu.parallel.sharding import DEFAULT_RULES, spec_for


@dataclasses.dataclass(frozen=True)
class _ActEnv:
    mesh: Mesh
    rules: Mapping


_env: contextvars.ContextVar[Optional[_ActEnv]] = contextvars.ContextVar(
    "shifu_tpu_act_env", default=None
)


@contextlib.contextmanager
def activation_sharding(mesh: Mesh, rules: Mapping = DEFAULT_RULES):
    """Enable ``constrain`` within this (tracing) scope."""
    token = _env.set(_ActEnv(mesh, rules))
    try:
        yield
    finally:
        _env.reset(token)


@contextlib.contextmanager
def no_activation_sharding():
    """Disable ``constrain`` within this (tracing) scope.

    Subsystems that manage sharding END-TO-END through a partial-manual
    shard_map (the 1F1B pipeline) suppress the ambient constraints while
    tracing their body: auto-axis layouts propagate from the shard_map's
    inputs, and mixing ambient per-activation constraints with the
    body's own reshards has tripped XLA SPMD partitioner internal
    checks on 3-axis (pp x tp x fsdp) meshes.
    """
    token = _env.set(None)
    try:
        yield
    finally:
        _env.reset(token)


def current_env() -> Optional[_ActEnv]:
    """The active (mesh, rules) pair, or None outside activation_sharding.

    Lets ops discover the mesh during tracing (e.g. the ring-attention
    dispatch needs it to build a shard_map) without threading the mesh
    through every model signature.
    """
    return _env.get()


def shard_map_compat(f, *, mesh, in_specs, out_specs, axis_names, check_vma=False):
    """``jax.shard_map`` across the jax versions this repo meets.

    jax >= 0.6 spells partial-manual as ``axis_names=`` + ``check_vma=``;
    older jax (0.4.x) spells the same program ``auto=`` (the complement
    set) + ``check_rep=`` on ``jax.experimental.shard_map.shard_map``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            axis_names=axis_names,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        auto=frozenset(mesh.axis_names) - set(axis_names),
    )


def constrain(x: jax.Array, logical: Sequence[Optional[str]]) -> jax.Array:
    """Pin ``x``'s sharding by logical axis names; identity without context.

    Divisibility/uniqueness fall back to replication per-dimension (see
    sharding.spec_for), so tiny shapes never fail on big meshes.
    """
    env = _env.get()
    if env is None:
        return x
    if len(logical) != x.ndim:
        raise ValueError(
            f"constrain: {len(logical)} names for rank-{x.ndim} array"
        )
    spec = spec_for(x.shape, logical, env.mesh, env.rules)

    # Inside a partial-manual shard_map (e.g. the pp pipeline), the trace's
    # abstract mesh marks the manual axes and rejects NamedShardings built
    # from the outer all-Auto mesh. Drop the manual axes (they're already
    # fixed by the shard_map) and constrain with a bare PartitionSpec,
    # which binds to the context mesh.
    try:
        from jax.sharding import AxisType, get_abstract_mesh
    except ImportError:
        # Older jax (< 0.5: no AxisType / abstract-mesh axis types) has
        # no partial-manual trace state to consult — constrain with the
        # context mesh directly (plain-mesh paths are unaffected; the
        # shard_map pipelines manage their own sharding end-to-end and
        # suppress ambient constraints via no_activation_sharding).
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(env.mesh, spec)
        )

    cur = get_abstract_mesh()
    if not cur.empty and any(t == AxisType.Manual for t in cur.axis_types):
        manual = {
            name
            for name, t in zip(cur.axis_names, cur.axis_types)
            if t == AxisType.Manual
        }
        clean = []
        for entry in spec:
            if entry is None:
                clean.append(None)
            elif isinstance(entry, str):
                clean.append(None if entry in manual else entry)
            else:
                kept = tuple(a for a in entry if a not in manual)
                clean.append(kept if kept else None)
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.PartitionSpec(*clean)
        )
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(env.mesh, spec)
    )
