"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context attention where no device ever holds the full KV: the
sequence is sharded over ``sp``, queries stay put, and K/V chunks rotate
around the ring via ``jax.lax.ppermute`` while each device folds every
visiting chunk into an online softmax (the same running (m, l, acc)
recurrence the flash kernel uses, here across devices instead of across
VMEM blocks). Peak per-device attention memory is O(S/P * S/P) scores and
O(S/P) KV — sequence length scales linearly with the ring size.

TPU mapping: ppermute between ring neighbours rides the ICI torus, and
because the ppermute of the *current* chunk and the attention compute on
it have no data dependency, XLA's latency-hiding scheduler overlaps the
transfer with the matmuls — the classic ring-attention compute/comm
overlap falls out of the dataflow with no manual double buffering.

Gradients flow through ``lax.scan`` + ``ppermute`` by plain autodiff
(ppermute's transpose is the inverse rotation); the scan body is
rematerialised per ring step so the backward never stores P score
matrices at once.

Causal note: with CONTIGUOUS sequence chunks, device i's chunks
j > i are entirely masked; the fold is skipped via ``lax.cond`` (the
chunk still rides the ring — other devices need it), so late ring
steps cost only the ppermute for early devices — FLOPs are balanced by
the skip, but TIME is not: device 0 folds once while device P-1 folds
P times, and the lockstep ppermutes make everyone wait for the busiest
device each step.

The ZIGZAG layout fixes the time imbalance: the global sequence is
split into 2P half-chunks and device i holds half-chunks ``i`` and
``2P-1-i`` (one early, one late). Per visiting ring chunk the fold
decomposes into (query half, kv half) PAIRS, each skipped or computed
by the same positional-relevance rule; causal work per device becomes
uniform — every device computes exactly 2P+1 half-pair blocks over the
ring (vs. i+1 full blocks, i.e. 2(i+1) half-pairs, contiguous), and
per ring step the skew is at most one half-pair instead of a whole
fold. ``ring_fold_counts`` exposes the analytic per-device counts (the
same relevance rule the traced code runs) so tests can assert the
balance. ``ring_attention_sharded(layout="zigzag")`` reorders the
globally-contiguous sequence into the zigzag placement on entry and
inverts it on exit, so callers keep contiguous semantics.

Sliding windows extend the same relevance rule: half-pairs entirely
below ``q_pos - window`` skip, keeping long-context windowed ring
attention O(S * window / P) compute per device in either layout.

Gemma-2 tanh logit soft-capping (``softcap=``) hooks into every fold's
partial attention (scores capped before the mask bias, exactly where
the XLA and flash paths cap); the cross-chunk (m, l, acc) merge is
cap-agnostic, and the backward is plain autodiff through the tanh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shifu_tpu.ops.attention import NEG_INF


def _partial_attention(q, k, v, bias, scale, softcap=None):
    """Unnormalised blockwise attention with GQA.

    q: (b, sq, h, d); k/v: (b, sk, h_kv, d); bias: (b, sq, sk) additive.
    ``softcap``: Gemma-2 tanh logit capping, applied to the scaled
    scores BEFORE the additive mask bias (same placement as the XLA
    and flash paths — the NEG_INF bias must stay un-capped).
    Returns (acc, m, l): acc (b, sq, h, d) f32 = sum_j exp(s - m) v;
    m, l (b, sq, h) f32 row max / normaliser.
    """
    b, sq, h, d = q.shape
    _, sk, h_kv, _ = k.shape
    group = h // h_kv
    qg = q.reshape(b, sq, h_kv, group, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bqhgk", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    s = s + bias[:, :, None, None, :]
    m = jnp.max(s, axis=-1)                          # (b, sq, h_kv, g)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bqhgk,bkhd->bqhgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return (
        acc.reshape(b, sq, h, d),
        m.reshape(b, sq, h),
        l.reshape(b, sq, h),
    )


def _layout_blocks(layout: str, axis_size: int, s_local: int):
    """Static (lo, hi, chunk_index_fn) list describing how a device's
    local s_local positions map to global half-chunks.

    contiguous: one block — device d holds global chunk d.
    zigzag: two half-blocks — device d holds half-chunks d and
      2P-1-d of the 2P-way split (one early, one late), which is what
      balances causal work across devices (module docstring).
    ``chunk_index_fn(d)`` works on python ints AND traced scalars, so
    the same rule drives the compiled skip conds and the analytic
    ``ring_fold_counts``.
    """
    if layout == "zigzag":
        hc = s_local // 2
        return [
            (0, hc, lambda d: d),
            (hc, 2 * hc, lambda d: 2 * axis_size - 1 - d),
        ]
    if layout == "contiguous":
        return [(0, s_local, lambda d: d)]
    raise ValueError(f"unknown ring layout {layout!r}")


def _pair_relevant(q_first, q_last, k_first, k_last, causal, window):
    """Whether a (query block, kv block) pair has ANY visible entry,
    from the blocks' first/last global positions. Works on python ints
    (ring_fold_counts) and traced scalars (the lax.cond predicates)."""
    if not causal:
        return (
            jnp.bool_(True)
            if isinstance(q_first, jax.Array)
            else True
        )
    r = k_first <= q_last
    if window is not None:
        r = r & (k_last > q_first - window)
    return r


def ring_fold_counts(
    layout: str,
    axis_size: int,
    s_local: int,
    *,
    causal: bool = True,
    window: Optional[int] = None,
):
    """Analytic per-device computed-block counts over a full ring pass,
    in units of (q block x kv block) pairs actually folded — the SAME
    relevance rule the compiled code conds on, so tests can assert the
    zigzag layout's balance without introspecting traced code. Note the
    units differ between layouts (zigzag blocks are half-sized), so
    compare balance within a layout, FLOPs across layouts by weighting
    with block area."""
    blocks = _layout_blocks(layout, axis_size, s_local)
    size = {
        "contiguous": s_local,
        "zigzag": s_local // 2,
    }[layout]
    counts = []
    for dev in range(axis_size):
        n = 0
        for src in range(axis_size):
            for _, _, q_ci in blocks:
                for _, _, k_ci in blocks:
                    q_lo = q_ci(dev) * size
                    k_lo = k_ci(src) * size
                    if _pair_relevant(
                        q_lo, q_lo + size - 1, k_lo, k_lo + size - 1,
                        causal, window,
                    ):
                        n += 1
        counts.append(n)
    return counts


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    softcap: Optional[float] = None,
    layout: str = "contiguous",
):
    """Per-shard ring attention; call inside shard_map over ``axis_name``.

    Args (all local shards; the sequence axis is sharded over the ring):
      q: (b, s_local, h, d).
      k, v: (b, s_local, h_kv, d).
      causal: causal mask over *global* positions.
      scale: score scale; defaults to head_dim ** -0.5.
      segment_ids: optional local (b, s_local) packing segments; the KV
        segment shard travels around the ring with its chunk.
      window: sliding-window attention — query i sees keys in
        (i - window, i] in GLOBAL positions. Requires ``causal``.
        Blocks entirely out of window skip their fold (module
        docstring), so compute scales with the window, not S.
      softcap: Gemma-2 tanh attention-logit capping, applied inside
        every fold's partial attention before its mask bias — the
        per-chunk (m, l, acc) merge is cap-agnostic, so the hook costs
        one elementwise per visiting chunk and composes with
        window/zigzag/segments.
      layout: "contiguous" (device i holds positions
        [i*s_local, (i+1)*s_local)) or "zigzag" (device i holds global
        half-chunks i and 2P-1-i — causal time balance; the caller owns
        placing the data accordingly, e.g. ring_attention_sharded).

    Returns: (b, s_local, h, d) in q.dtype.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    # jax.lax.axis_size landed in 0.6; psum(1, axis) is the old spelling
    # (a compile-time constant either way).
    axis_size = (
        jax.lax.axis_size(axis_name)
        if hasattr(jax.lax, "axis_size")
        else int(jax.lax.psum(1, axis_name))
    )
    my = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if layout == "zigzag" and s_local % 2:
        raise ValueError("zigzag needs an even per-device sequence")
    if scale is None:
        scale = d**-0.5
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    blocks = _layout_blocks(layout, axis_size, s_local)

    def block_pos(ci, size):
        return ci * size + jnp.arange(size)

    def fold_pair(m_b, l_b, acc_b, qb, qseg, qpos, kb, vb, ks_b, kpos):
        """Merge ONE (q block, kv block) pair into the q block's
        running (m_b, l_b, acc_b) — all operands are the BLOCK slices,
        so nothing here scatters (structurally identical to the whole-
        chunk fold; .at[].set updates of the full carry tripped the
        shardy partitioner when this shard_map nests under a scanned,
        rematerialised pjit block)."""
        # Combine masks as booleans and apply NEG_INF exactly once: adding
        # two NEG_INF biases would overflow f32 to -inf, and a fully-masked
        # row then hits exp((-inf) - (-inf)) = NaN in _partial_attention.
        allowed = jnp.ones((b, qb.shape[1], kb.shape[1]), bool)
        if causal:
            allowed = jnp.logical_and(
                allowed, (kpos[None, :] <= qpos[:, None])[None]
            )
        if window is not None:
            allowed = jnp.logical_and(
                allowed, (kpos[None, :] > qpos[:, None] - window)[None]
            )
        if segment_ids is not None:
            allowed = jnp.logical_and(
                allowed, qseg[:, :, None] == ks_b[:, None, :]
            )
        bias = jnp.where(allowed, 0.0, NEG_INF)

        # Partially-masked rows inside a relevant pair contribute
        # m_t == NEG_INF; the exp() terms below zero them out. Pairs
        # masked ENTIRELY never reach here (the relevance cond skips).
        acc_t, m_t, l_t = _partial_attention(
            qb, kb, vb, bias, scale, softcap=softcap
        )
        m_new = jnp.maximum(m_b, m_t)
        a_old = jnp.exp(m_b - m_new)
        a_new = jnp.exp(m_t - m_new)
        acc_b = acc_b * a_old[..., None] + acc_t * a_new[..., None]
        l_b = l_b * a_old + l_t * a_new
        return m_new, l_b, acc_b

    def maybe_fold(m, l, acc, k_cur, v_cur, ks_cur, t):
        """Fold every (q block, kv block) pair of the visiting chunk
        whose position ranges overlap the mask — lax.cond executes only
        one branch at runtime, so skipped pairs cost zero FLOPs (the
        ppermute still runs; other devices need the chunk). Each q
        block's state folds independently; the carry reassembles by
        concatenation (single block: passthrough)."""
        src = (my - t) % axis_size
        size = s_local // len(blocks)
        parts = []
        for qlo, qhi, q_ci in blocks:
            qc = q_ci(my)
            qpos = block_pos(qc, size)
            qb = q[:, qlo:qhi]
            qseg = (
                segment_ids[:, qlo:qhi]
                if segment_ids is not None
                else None
            )
            m_b = m[:, qlo:qhi]
            l_b = l[:, qlo:qhi]
            acc_b = acc[:, qlo:qhi]
            for klo, khi, k_ci in blocks:
                kc = k_ci(src)
                kpos = block_pos(kc, size)
                relevant = _pair_relevant(
                    qc * size, qc * size + size - 1,
                    kc * size, kc * size + size - 1,
                    causal, window,
                )

                def do(mm, ll, aa, kk, vv, ks, kp,
                       _qb=qb, _qseg=qseg, _qpos=qpos):
                    return fold_pair(
                        mm, ll, aa, _qb, _qseg, _qpos, kk, vv, ks, kp
                    )

                m_b, l_b, acc_b = jax.lax.cond(
                    relevant,
                    do,
                    lambda mm, ll, aa, kk, vv, ks, kp: (mm, ll, aa),
                    m_b, l_b, acc_b,
                    k_cur[:, klo:khi], v_cur[:, klo:khi],
                    ks_cur[:, klo:khi], kpos,
                )
            parts.append((m_b, l_b, acc_b))
        if len(parts) == 1:
            return parts[0]
        return tuple(
            jnp.concatenate([p[i] for p in parts], axis=1)
            for i in range(3)
        )

    def step(carry, t):
        k_cur, v_cur, ks_cur, m, l, acc = carry
        m, l, acc = maybe_fold(m, l, acc, k_cur, v_cur, ks_cur, t)
        k_nxt, v_nxt, ks_nxt = jax.lax.ppermute(
            (k_cur, v_cur, ks_cur), axis_name, perm
        )
        return (k_nxt, v_nxt, ks_nxt, m, l, acc), None

    m0 = jnp.full((b, s_local, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s_local, h), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    ks0 = (
        segment_ids
        if segment_ids is not None
        # Dummy so the carry structure is static; never read. (Cost: one
        # (b, s_local) int32 per hop — noise next to the K/V payload.)
        else jnp.zeros((b, s_local), jnp.int32)
    )
    # Scan the first P-1 steps (each rotates KV onward); the final chunk
    # folds outside the scan with no trailing ppermute — that last
    # rotation would be pure wasted ICI traffic. Both parts recompute in
    # the backward (checkpoint) so P score matrices never coexist.
    carry = (k, v, ks0, m0, l0, acc0)
    if axis_size > 1:
        carry, _ = jax.lax.scan(
            jax.checkpoint(step), carry, jnp.arange(axis_size - 1)
        )
    k_l, v_l, ks_l, m, l, acc = carry
    m, l, acc = jax.checkpoint(maybe_fold)(
        m, l, acc, k_l, v_l, ks_l, jnp.int32(axis_size - 1)
    )
    # A query sees every key exactly once around the ring, so for causal
    # self-attention l >= 1 always (each query attends at least itself);
    # fully-masked rows under adversarial segment ids degenerate to the
    # uniform softmax over NEG_INF scores (l = S, mean-of-v) — the same
    # thing the XLA reference computes. No zero-division guard is needed.
    return (acc / l[..., None]).astype(q.dtype)


def ring_shardable(
    mesh: Mesh,
    q_shape,
    kv_shape,
    *,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
) -> bool:
    """Whether ring_attention_sharded's shard_map specs admit these shapes.

    Lives beside the specs so the eligibility rule and the axis mapping
    can't drift apart. shard_map is strict — every mapped dim must divide
    evenly (no per-dim replication fallback like ctx.constrain has) — and
    the ring additionally needs self-attention (q_len == kv_len).
    """
    if mesh.shape.get(seq_axis, 1) <= 1:
        return False
    dp_sz = 1
    for a in batch_axes:
        dp_sz *= mesh.shape.get(a, 1)
    sp_sz = mesh.shape[seq_axis]
    tp_sz = mesh.shape.get(head_axis, 1)
    b, sq, h, _ = q_shape
    _, skv, h_kv, _ = kv_shape
    return (
        sq == skv
        and b % dp_sz == 0
        and sq % sp_sz == 0
        and h % tp_sz == 0
        and h_kv % tp_sz == 0
    )


def zigzag_order(seq_len: int, axis_size: int):
    """Permutation placing a contiguous global sequence into the zigzag
    layout: position j of the permuted sequence holds original position
    ``order[j]``; device d's shard (the d-th s_local block of the
    permuted sequence) then holds half-chunks d and 2P-1-d."""
    hc = seq_len // (2 * axis_size)
    order = []
    for dv in range(axis_size):
        order.extend(range(dv * hc, (dv + 1) * hc))
        late = 2 * axis_size - 1 - dv
        order.extend(range(late * hc, (late + 1) * hc))
    import numpy as np

    return np.asarray(order, np.int32)


def ring_attention_sharded(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    softcap: Optional[float] = None,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
    layout: str = "contiguous",
):
    """shard_map wrapper: global (b, s, h, d) arrays → ring attention.

    Batch rides dp/fsdp, sequence rides sp (the ring), heads ride tp —
    attention is per-head so the tp split needs no collective here; only
    sp communicates (neighbour ppermute on the ICI torus).

    ``layout="zigzag"`` balances causal work across the ring in TIME
    (module docstring): the globally-contiguous inputs are permuted
    into the zigzag placement before the shard_map and the output is
    permuted back, so the caller's semantics don't change. The two
    permutations are one sharded gather each (XLA lowers them to
    neighbour exchanges); their cost is linear in S versus the ring's
    quadratic attention, and buys up to ~2x less tail latency at large
    P (the contiguous layout's last device folds P blocks while the
    first folds one)."""
    if layout == "zigzag":
        s = q.shape[1]
        sp_sz = mesh.shape.get(seq_axis, 1)
        if s % (2 * sp_sz):
            raise ValueError(
                f"zigzag needs seq {s} divisible by 2*sp ({2 * sp_sz})"
            )
        order = jnp.asarray(zigzag_order(s, sp_sz))
        inv = jnp.argsort(order)
        q = jnp.take(q, order, axis=1)
        k = jnp.take(k, order, axis=1)
        v = jnp.take(v, order, axis=1)
        if segment_ids is not None:
            segment_ids = jnp.take(segment_ids, order, axis=1)

    qspec = P(batch_axes, seq_axis, head_axis, None)
    sspec = P(batch_axes, seq_axis)
    in_specs = (qspec, qspec, qspec)
    args = (q, k, v)
    if segment_ids is not None:
        in_specs += (sspec,)
        args += (segment_ids,)

    # shard_map_compat: jax >= 0.6 spells this jax.shard_map; older jax
    # needs jax.experimental.shard_map (the compat shim maps the kwargs)
    # — full-manual over every mesh axis either way.
    from shifu_tpu.parallel.ctx import shard_map_compat

    @functools.partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=qspec,
        axis_names=tuple(mesh.axis_names),
        check_vma=False,
    )
    def mapped(q, k, v, *rest):
        segs = rest[0] if rest else None
        return ring_attention(
            q, k, v, axis_name=seq_axis, causal=causal, scale=scale,
            segment_ids=segs, window=window, softcap=softcap,
            layout=layout,
        )

    out = mapped(*args)
    if layout == "zigzag":
        out = jnp.take(out, inv, axis=1)
    return out
