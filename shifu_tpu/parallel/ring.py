"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context attention where no device ever holds the full KV: the
sequence is sharded over ``sp``, queries stay put, and K/V chunks rotate
around the ring via ``jax.lax.ppermute`` while each device folds every
visiting chunk into an online softmax (the same running (m, l, acc)
recurrence the flash kernel uses, here across devices instead of across
VMEM blocks). Peak per-device attention memory is O(S/P * S/P) scores and
O(S/P) KV — sequence length scales linearly with the ring size.

TPU mapping: ppermute between ring neighbours rides the ICI torus, and
because the ppermute of the *current* chunk and the attention compute on
it have no data dependency, XLA's latency-hiding scheduler overlaps the
transfer with the matmuls — the classic ring-attention compute/comm
overlap falls out of the dataflow with no manual double buffering.

Gradients flow through ``lax.scan`` + ``ppermute`` by plain autodiff
(ppermute's transpose is the inverse rotation); the scan body is
rematerialised per ring step so the backward never stores P score
matrices at once.

Causal note: with contiguous sequence chunks, device i's chunks
j > i are entirely masked; the fold is skipped via ``lax.cond`` (the
chunk still rides the ring — other devices need it), so late ring
steps cost only the ppermute for early devices — the classic causal
imbalance in time, but not in FLOPs. Sliding windows
(``window``) extend the same skip: chunks entirely below
``q_pos - window`` contribute nothing and their fold is skipped too,
making long-context windowed ring attention O(S * window / P) compute
per device.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shifu_tpu.ops.attention import NEG_INF


def _partial_attention(q, k, v, bias, scale):
    """Unnormalised blockwise attention with GQA.

    q: (b, sq, h, d); k/v: (b, sk, h_kv, d); bias: (b, sq, sk) additive.
    Returns (acc, m, l): acc (b, sq, h, d) f32 = sum_j exp(s - m) v;
    m, l (b, sq, h) f32 row max / normaliser.
    """
    b, sq, h, d = q.shape
    _, sk, h_kv, _ = k.shape
    group = h // h_kv
    qg = q.reshape(b, sq, h_kv, group, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bqhgk", qg, k, preferred_element_type=jnp.float32
    ) * scale
    s = s + bias[:, :, None, None, :]
    m = jnp.max(s, axis=-1)                          # (b, sq, h_kv, g)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bqhgk,bkhd->bqhgd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return (
        acc.reshape(b, sq, h, d),
        m.reshape(b, sq, h),
        l.reshape(b, sq, h),
    )


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
):
    """Per-shard ring attention; call inside shard_map over ``axis_name``.

    Args (all local shards; the sequence axis is sharded over the ring):
      q: (b, s_local, h, d).
      k, v: (b, s_local, h_kv, d).
      causal: causal mask over *global* positions (contiguous chunks:
        device i holds positions [i*s_local, (i+1)*s_local)).
      scale: score scale; defaults to head_dim ** -0.5.
      segment_ids: optional local (b, s_local) packing segments; the KV
        segment shard travels around the ring with its chunk.
      window: sliding-window attention — query i sees keys in
        (i - window, i] in GLOBAL positions. Requires ``causal``.
        Chunks entirely out of window skip their fold (module
        docstring), so compute scales with the window, not S.

    Returns: (b, s_local, h, d) in q.dtype.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    axis_size = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if scale is None:
        scale = d**-0.5
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    q_pos = my * s_local + jnp.arange(s_local)       # global query positions

    def fold(m, l, acc, k_cur, v_cur, ks_cur, t):
        """Merge one visiting KV chunk into the running (m, l, acc)."""
        src = (my - t) % axis_size                   # chunk's home device
        kv_pos = src * s_local + jnp.arange(s_local)

        # Combine masks as booleans and apply NEG_INF exactly once: adding
        # two NEG_INF biases would overflow f32 to -inf, and a fully-masked
        # row then hits exp((-inf) - (-inf)) = NaN in _partial_attention.
        allowed = jnp.ones((b, s_local, s_local), bool)
        if causal:
            allowed = jnp.logical_and(
                allowed, (kv_pos[None, :] <= q_pos[:, None])[None]
            )
        if window is not None:
            allowed = jnp.logical_and(
                allowed, (kv_pos[None, :] > q_pos[:, None] - window)[None]
            )
        if segment_ids is not None:
            allowed = jnp.logical_and(
                allowed, segment_ids[:, :, None] == ks_cur[:, None, :]
            )
        bias = jnp.where(allowed, 0.0, NEG_INF)

        # Partially-masked rows inside a relevant chunk contribute
        # m_t == NEG_INF; the exp() terms below zero them out. Chunks
        # masked ENTIRELY (causal future / out of window) never reach
        # here — maybe_fold skips the fold via lax.cond.
        acc_t, m_t, l_t = _partial_attention(q, k_cur, v_cur, bias, scale)
        m_new = jnp.maximum(m, m_t)
        a_old = jnp.exp(m - m_new)
        a_new = jnp.exp(m_t - m_new)
        acc = acc * a_old[..., None] + acc_t * a_new[..., None]
        l = l * a_old + l_t * a_new
        return m_new, l, acc

    def maybe_fold(m, l, acc, k_cur, v_cur, ks_cur, t):
        """Fold unless the chunk is entirely masked (causal future /
        fully below the window), in which case pass (m, l, acc) through
        untouched — lax.cond executes only one branch at runtime, so the
        skipped chunk costs zero FLOPs (the ppermute still runs; other
        devices need the chunk)."""
        src = (my - t) % axis_size
        relevant = jnp.bool_(True)
        if causal:
            relevant = src <= my  # chunk not strictly in the future
            if window is not None:
                # Newest key of the chunk still visible to the OLDEST
                # local query: kv_max > q_min - window.
                relevant = relevant & (
                    (src + 1) * s_local - 1 > my * s_local - window
                )
        return jax.lax.cond(
            relevant,
            lambda ops: fold(*ops),
            lambda ops: (ops[0], ops[1], ops[2]),
            (m, l, acc, k_cur, v_cur, ks_cur, t),
        )

    def step(carry, t):
        k_cur, v_cur, ks_cur, m, l, acc = carry
        m, l, acc = maybe_fold(m, l, acc, k_cur, v_cur, ks_cur, t)
        k_nxt, v_nxt, ks_nxt = jax.lax.ppermute(
            (k_cur, v_cur, ks_cur), axis_name, perm
        )
        return (k_nxt, v_nxt, ks_nxt, m, l, acc), None

    m0 = jnp.full((b, s_local, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s_local, h), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    ks0 = (
        segment_ids
        if segment_ids is not None
        # Dummy so the carry structure is static; never read. (Cost: one
        # (b, s_local) int32 per hop — noise next to the K/V payload.)
        else jnp.zeros((b, s_local), jnp.int32)
    )
    # Scan the first P-1 steps (each rotates KV onward); the final chunk
    # folds outside the scan with no trailing ppermute — that last
    # rotation would be pure wasted ICI traffic. Both parts recompute in
    # the backward (checkpoint) so P score matrices never coexist.
    carry = (k, v, ks0, m0, l0, acc0)
    if axis_size > 1:
        carry, _ = jax.lax.scan(
            jax.checkpoint(step), carry, jnp.arange(axis_size - 1)
        )
    k_l, v_l, ks_l, m, l, acc = carry
    m, l, acc = jax.checkpoint(maybe_fold)(
        m, l, acc, k_l, v_l, ks_l, jnp.int32(axis_size - 1)
    )
    # A query sees every key exactly once around the ring, so for causal
    # self-attention l >= 1 always (each query attends at least itself);
    # fully-masked rows under adversarial segment ids degenerate to the
    # uniform softmax over NEG_INF scores (l = S, mean-of-v) — the same
    # thing the XLA reference computes. No zero-division guard is needed.
    return (acc / l[..., None]).astype(q.dtype)


def ring_shardable(
    mesh: Mesh,
    q_shape,
    kv_shape,
    *,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
) -> bool:
    """Whether ring_attention_sharded's shard_map specs admit these shapes.

    Lives beside the specs so the eligibility rule and the axis mapping
    can't drift apart. shard_map is strict — every mapped dim must divide
    evenly (no per-dim replication fallback like ctx.constrain has) — and
    the ring additionally needs self-attention (q_len == kv_len).
    """
    if mesh.shape.get(seq_axis, 1) <= 1:
        return False
    dp_sz = 1
    for a in batch_axes:
        dp_sz *= mesh.shape.get(a, 1)
    sp_sz = mesh.shape[seq_axis]
    tp_sz = mesh.shape.get(head_axis, 1)
    b, sq, h, _ = q_shape
    _, skv, h_kv, _ = kv_shape
    return (
        sq == skv
        and b % dp_sz == 0
        and sq % sp_sz == 0
        and h % tp_sz == 0
        and h_kv % tp_sz == 0
    )


def ring_attention_sharded(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
):
    """shard_map wrapper: global (b, s, h, d) arrays → ring attention.

    Batch rides dp/fsdp, sequence rides sp (the ring), heads ride tp —
    attention is per-head so the tp split needs no collective here; only
    sp communicates (neighbour ppermute on the ICI torus).
    """
    qspec = P(batch_axes, seq_axis, head_axis, None)
    sspec = P(batch_axes, seq_axis)
    in_specs = (qspec, qspec, qspec)
    args = (q, k, v)
    if segment_ids is not None:
        in_specs += (sspec,)
        args += (segment_ids,)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=qspec,
        check_vma=False,
    )
    def mapped(q, k, v, *rest):
        segs = rest[0] if rest else None
        return ring_attention(
            q, k, v, axis_name=seq_axis, causal=causal, scale=scale,
            segment_ids=segs, window=window,
        )

    return mapped(*args)
