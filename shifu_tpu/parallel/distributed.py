"""Multi-host (multi-process) runtime: init, hybrid meshes, host-local data.

A multi-host TPU pod runs one Python process per host; every process
executes the same program and owns a subset of the devices. Three pieces
make the framework's single-host code work unchanged at pod scale:

  * :func:`initialize` — bring up the JAX distributed runtime (GRPC
    coordination service). On TPU pods all parameters auto-detect from
    the metadata server; elsewhere pass coordinator/process counts (or
    export JAX_COORDINATOR_ADDRESS etc.). No-op when single-process.
  * :class:`HybridMeshPlan` — meshes that respect the two-tier network:
    ICI (fast, within a slice) and DCN (slower, between slices). Each
    logical axis is the product of its DCN and ICI extents, with DCN
    placed on the outer (slower-varying) tier — put dp/fsdp there, keep
    tp/sp/pp inside a slice, and gradient all-reduces are the only
    cross-slice traffic (the scaling-book recipe).
  * :func:`shard_host_batch` — per-process data feeding: every host
    loads only its own rows (e.g. PackedLoader over a host-sharded file
    set) and ``jax.make_array_from_process_local_data`` assembles the
    logical global batch without any cross-host gather.

Reference parity note: the upstream reference (klyan/shifu) is an empty
repository (SURVEY.md); there is no reference distributed backend — this
is the jax.distributed + Mesh idiom that replaces a NCCL/MPI stack.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding

from shifu_tpu.parallel.mesh import MESH_AXES, MeshPlan
from shifu_tpu.parallel import sharding as shd


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Start the distributed runtime if this is a multi-process job.

    Returns True when initialization ran, False for a single-process run
    (nothing to do). Safe to call unconditionally at program start —
    mirrors how a torch.distributed/NCCL stack would init, but the
    coordination here is only for control-plane bootstrap: the actual
    collectives are XLA programs over ICI/DCN, no process-level
    communicator objects exist.
    """
    explicit = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    # Auto-detect only a genuinely multi-host TPU job: a single-host TPU VM
    # also exports TPU_WORKER_HOSTNAMES (= "localhost"), so require >1 host.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    on_multihost_tpu = len([h for h in hostnames.split(",") if h]) > 1 or bool(
        os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if not explicit and not on_multihost_tpu:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def is_coordinator() -> bool:
    """True on the process that should write checkpoints metadata / logs."""
    return jax.process_index() == 0


@dataclasses.dataclass(frozen=True)
class HybridMeshPlan:
    """Two-tier mesh: per-axis extents split into DCN (outer) x ICI (inner).

    Example — 4 slices of 256 chips, fsdp across slices, tp/sp within::

        mesh = HybridMeshPlan(
            dcn=MeshPlan(fsdp=4),
            ici=MeshPlan(fsdp=16, sp=4, tp=4),
        ).build()

    gives a (dp, fsdp, ep, pp, sp, tp) = (1, 64, 1, 1, 4, 4) mesh where
    the 4-way outer factor of fsdp crosses DCN and everything else stays
    on ICI.
    """

    dcn: MeshPlan = MeshPlan()
    ici: MeshPlan = MeshPlan()

    @property
    def shape(self) -> tuple:
        return tuple(d * i for d, i in zip(self.dcn.shape, self.ici.shape))

    @property
    def n_devices(self) -> int:
        return int(np.prod(self.shape))

    def build(self, devices=None) -> Mesh:
        if devices is None:
            devices = jax.devices()
        if self.n_devices != len(devices):
            raise ValueError(
                f"HybridMeshPlan {self.shape} needs {self.n_devices} "
                f"devices, got {len(devices)}"
            )
        multi_slice = (
            devices[0].platform == "tpu"
            and getattr(devices[0], "slice_index", None) is not None
            and any(self.dcn.shape[i] > 1 for i in range(len(MESH_AXES)))
        )
        if multi_slice:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                self.ici.shape, self.dcn.shape, devices=devices
            )
        else:
            # Single slice: the DCN tier is vacuous; shared helper keeps
            # the topology-aware ICI ordering (tp on torus neighbours).
            from shifu_tpu.parallel.mesh import device_array

            dev_array = device_array(self.shape, devices)
        return Mesh(dev_array, MESH_AXES)


def shard_host_batch(
    batch: Mapping[str, np.ndarray],
    mesh: Mesh,
    rules=None,
    *,
    microbatched: bool = False,
):
    """Assemble a GLOBAL batch from per-process LOCAL rows.

    Each process passes only its own slice of the global batch (global
    batch axis = local rows x process count, in process-index order).
    Uses ``jax.make_array_from_process_local_data``, so no host ever
    materialises other hosts' data. With one process this equals
    parallel.shard_batch.
    """
    rules = rules or shd.DEFAULT_RULES
    lead = (None,) if microbatched else ()

    def put(x):
        x = np.asarray(x)
        names = lead + ("batch", "seq")
        logical = names[: x.ndim] + (None,) * max(0, x.ndim - len(names))
        global_shape = list(x.shape)
        axis = 1 if microbatched else 0
        has_batch_axis = axis < x.ndim
        if has_batch_axis:  # leaves without a batch axis stay replicated
            global_shape[axis] *= jax.process_count()
        spec = shd.spec_for(tuple(global_shape), logical, mesh, rules)
        if jax.process_count() > 1 and has_batch_axis:
            # Per-process assembly needs the batch axis sharded into (a
            # multiple of) process_count pieces; a replicated or
            # under-sharded batch axis (pure tp/pp meshes, or the
            # divisibility rail falling back) cannot be built from local
            # rows — fail loudly before make_array_from_process_local_data
            # produces its opaque shape-mismatch error.
            entry = spec[axis] if len(spec) > axis else None
            names = (
                (entry,) if isinstance(entry, str) else tuple(entry or ())
            )
            extent = int(np.prod([mesh.shape[n] for n in names])) if names else 1
            if extent % jax.process_count() != 0:
                raise ValueError(
                    f"batch axis shards over {extent} devices, which is "
                    f"not a multiple of process_count="
                    f"{jax.process_count()}; per-process assembly needs "
                    "the batch axis sharded across all hosts (resize the "
                    "mesh's data axes)"
                )
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), x, tuple(global_shape)
        )

    return jax.tree_util.tree_map(put, batch)
