"""Logical-axis → mesh-axis sharding rules.

The model layer names every parameter dimension with a *logical* axis
(core.module.ParamSpec.axes). This module turns those names into
``PartitionSpec``/``NamedSharding`` trees for pjit, applying two safety
rails per tensor:

  * divisibility — a logical axis only maps onto a mesh axis if the
    dimension size divides by the mesh axis extent; otherwise that
    dimension is replicated (tiny test configs stay valid on big meshes).
  * uniqueness — a mesh axis may appear at most once in one tensor's spec;
    later dimensions claiming an already-used mesh axis are replicated.

Rules are an ordered mapping ``logical name -> mesh axis | tuple | None``.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shifu_tpu.core.module import Module, ParamSpec

MeshAxes = Union[None, str, tuple]

# Default rules for the transformer family. fsdp shards the embed dimension
# of weights (ZeRO-3); tp shards heads/mlp/vocab; pp shards the stacked
# layers axis; experts ride ep. "act_experts" pins the leading E axis of
# the (E, b, C, d) MoE dispatch buffers onto ep — BOTH dispatch
# implementations (grouped and einsum-oracle, ops/moe.py) constrain that
# same layout, so training and ep-sharded serving (MeshPlan.serving /
# `serve --mesh ep=`) get the identical token<->expert all-to-all.
DEFAULT_RULES: dict = {
    "layers": "pp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "experts": "ep",
    "expert_mlp": "tp",
    "head_dim": None,
    # activation axes
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "act_embed": None,
    "act_heads": "tp",
    "act_mlp": "tp",
    "act_vocab": "tp",
    "act_experts": "ep",
}


def _mesh_size(mesh: Mesh, axes: MeshAxes) -> int:
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def spec_for(
    shape: Sequence[int],
    logical: Sequence[Optional[str]],
    mesh: Mesh,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
) -> P:
    """PartitionSpec for one tensor, applying divisibility + uniqueness."""
    used: set = set()
    out = []
    for dim, name in zip(shape, logical):
        mapped = rules.get(name) if name is not None else None
        if mapped is None:
            out.append(None)
            continue
        axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
        if any(a in used for a in axes):
            out.append(None)
            continue
        if dim % _mesh_size(mesh, axes) != 0:
            out.append(None)
            continue
        used.update(axes)
        out.append(mapped if isinstance(mapped, str) else tuple(axes))
    # Trim trailing Nones (cosmetic only).
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def param_specs_tree(
    module: Module, mesh: Mesh, rules: Mapping[str, MeshAxes] = DEFAULT_RULES
):
    """Tree of PartitionSpec matching the module's params tree."""
    specs = module.specs()

    def one(s: ParamSpec) -> P:
        return spec_for(s.shape, s.axes, mesh, rules)

    return jax.tree_util.tree_map(
        one, specs, is_leaf=lambda x: isinstance(x, ParamSpec)
    )


def param_shardings(
    module: Module, mesh: Mesh, rules: Mapping[str, MeshAxes] = DEFAULT_RULES
):
    """Tree of NamedSharding matching the module's params tree."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, p),
        param_specs_tree(module, mesh, rules),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(
    module: Module,
    params,
    mesh: Mesh,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
):
    """Place an EXISTING params tree into its sharded layout (e.g.
    checkpoint- or HF-loaded weights before mesh serving). For fresh
    params prefer :func:`init_sharded`, which never materialises a full
    host copy."""
    return jax.device_put(params, param_shardings(module, mesh, rules))


def init_sharded(
    module: Module,
    rng: jax.Array,
    mesh: Mesh,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
):
    """Initialise parameters directly into their shards.

    The init runs under jit with ``out_shardings`` set, so every weight is
    created on its owning devices — no host-side full copy, which is what
    makes >HBM-sized models initialisable at all.
    """
    shardings = param_shardings(module, mesh, rules)
    init_fn = jax.jit(
        lambda key: module.init(key), out_shardings=shardings
    )
    return init_fn(rng)


def abstract_params(
    module: Module,
    mesh: Optional[Mesh] = None,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
):
    """Params-shaped tree of ShapeDtypeStruct (with NamedShardings if a mesh
    is given). The single lowering used both for jit in/out shardings
    (train.step) and checkpoint restore templates (checkpoint) — keeping
    them structurally identical by construction.
    """
    is_spec = lambda x: isinstance(x, ParamSpec)

    def one(s: ParamSpec):
        sharding = None
        if mesh is not None:
            sharding = NamedSharding(mesh, spec_for(s.shape, s.axes, mesh, rules))
        return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding)

    return jax.tree_util.tree_map(one, module.specs(), is_leaf=is_spec)


def batch_spec(mesh: Mesh, rules: Mapping[str, MeshAxes] = DEFAULT_RULES) -> P:
    """PartitionSpec for a (batch, seq) token array.

    Built with the sentinel shape (0, 0): 0 is divisible by every mesh axis
    extent, so spec_for's divisibility rail never fires here. Divisibility
    of real data is the caller's contract (batch % (dp*fsdp) == 0 etc.) —
    shape-aware callers should prefer shard_batch.
    """
    return spec_for((0, 0), ("batch", "seq"), mesh, rules)


def shard_batch(batch, mesh: Mesh, rules=DEFAULT_RULES, *, microbatched=False):
    """Device_put a host batch tree of (b, s[, ...]) arrays onto the mesh.

    With ``microbatched=True`` leaves are (microbatch, b, s[, ...]) — the
    leading scan axis is left unsharded and batch/seq shift right one dim.
    """
    lead = (None,) if microbatched else ()

    def put(x):
        x = jnp.asarray(x)
        names = lead + ("batch", "seq")
        logical = names[: x.ndim] + (None,) * max(0, x.ndim - len(names))
        spec = spec_for(x.shape, logical, mesh, rules)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)
