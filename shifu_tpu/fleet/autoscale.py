"""Elastic fleet control plane: SLO-headroom autoscaling, dynamic
prefill/decode role rebalancing, and envelope-paced batch backfill.

ROADMAP item 3's closing loop. Every signal and actuator this module
needs already exists — PR 12's ``/sloz`` publishes per-tier burn-rate
headroom, PR 11's backends report ``prefill_tok_per_ms`` EMAs and the
router counts disagg handoff outcomes per prefill host, PR 6's
drain/resume/readiness machinery plus PR 15's peer warmup make adding
or reshaping a host cheap. The :class:`AutoscaleController` is the
measure-and-act daemon that closes it, in the Autocomp spirit applied
one level up: fleet SHAPE (size, role mix, backfill pace) is picked by
measurement every tick, never by static assignment.

One ``tick()`` is one decision round against the router's ``/statz`` +
``/sloz``:

1. **Envelope** (dwell-independent): fold the fleet's worst HBM
   high-water fraction and the router-measured decode step time into
   the declared :class:`~shifu_tpu.fleet.envelope.Envelope`, and push
   the resulting batch-admission scale to the front-end
   (``POST /envelopez``) when it moved materially. A scrape gap (no
   signal measured anywhere) holds the last pushed scale.
2. **Scale** (hysteresis bands + min-dwell): min per-tier SLO headroom
   below the low-water mark activates the next parked standby host —
   readiness-gated through the bootstrap path (:func:`wait_ready`),
   then admitted via ``POST /fleetz`` where the router probes it again
   and peer-warms it (``maybe_peer_warm``). Headroom above the
   high-water mark drains and parks the emptiest ACTIVATED standby
   (the declared base fleet is never parked). Between the bands, and
   within ``dwell_s`` of the last action, the pool holds — the fleet
   never flaps at a boundary.
3. **Rebalance roles**: when the measured prefill/decode demand mix
   (per-role load averages + the per-tick delta of disagg handoff
   attempts) shifts past ``flip_margin``, one host is drained through
   the router, its role flipped via ``POST /rolez`` (legal only on an
   idle engine), readiness-gated until it advertises the new role, and
   resumed.

**Every actuator failure degrades to "do nothing and retry next
tick"**: an unreachable router skips the round, a dead standby leaves
the pool unchanged, a drain that never empties resumes the host
unflipped. The controller can always crash or stop without leaving
the fleet worse than it found it — the one deliberately asymmetric
case (a host that flipped but whose resume failed) is recorded as
``role_flip_failed`` with ``flipped=true`` so the operator knows the
router, not the host, needs the retry.

Every decision is visible three ways: ``autoscale_*`` flight events
and the ``shifu_autoscale_*`` / ``shifu_role_flips_total`` /
``shifu_envelope_*`` metric families on the ROUTER (reported via
``POST /autoscalez`` so one scrape shows traffic and reshaping
together), and the ``/statz`` ``autoscale`` block ``obs top`` renders.

Structure mirrors :class:`~shifu_tpu.fleet.rollout.RolloutController`:
injectable clock/sleep/backend-factory, a :class:`RouterAdmin` for all
router HTTP, fake-clock unit tests driving ``tick()`` directly and a
two-process acceptance walk driving ``run()`` against real backends
(tests/test_autoscale.py, tests/test_autoscale_fleet.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from shifu_tpu.fleet.backend import BackendClient, BackendError
from shifu_tpu.fleet.bootstrap import parse_fleet, wait_ready
from shifu_tpu.fleet.envelope import Envelope, parse_envelope_spec
from shifu_tpu.fleet.rollout import RolloutError, RouterAdmin

__all__ = [
    "AutoscaleController",
    "AutoscaleError",
    "AutoscalePolicy",
    "check_policy",
]


class AutoscaleError(RuntimeError):
    """The controller cannot run at all (e.g. the router is
    unreachable before the first tick). Mid-run failures never raise —
    they degrade to a skipped tick and a note."""


@dataclass(frozen=True)
class AutoscalePolicy:
    """The control loop's declared behavior. ``low_headroom`` /
    ``high_headroom`` are the hysteresis band over min per-tier SLO
    headroom (1 - burn; /sloz): below low activates a standby, above
    high parks one, between holds. ``dwell_s`` is the minimum time
    between pool/role ACTIONS (envelope pushes are exempt — pacing
    backfill is how the fleet avoids needing an action). ``tick_s``
    paces ``run()``. ``flip_margin`` is how many times busier one
    role's hosts must be than the other's before a role flip.
    ``min_backends`` floors the active pool — scale-down and role
    flips never drop the serving set below it."""

    low_headroom: float = 0.15
    high_headroom: float = 0.60
    dwell_s: float = 60.0
    tick_s: float = 5.0
    flip_margin: float = 2.0
    min_backends: int = 1

    def __post_init__(self):
        if not (0.0 <= self.low_headroom < self.high_headroom <= 1.0):
            raise ValueError(
                "watermarks must satisfy 0 <= low < high <= 1, got "
                f"low={self.low_headroom} high={self.high_headroom} — "
                "e.g. --low-headroom 0.15 --high-headroom 0.6"
            )
        if self.tick_s <= 0.0:
            raise ValueError(
                f"tick must be > 0s, got {self.tick_s} — e.g. --tick 5"
            )
        if self.dwell_s <= self.tick_s:
            raise ValueError(
                f"dwell ({self.dwell_s}s) must exceed the tick "
                f"({self.tick_s}s) or every tick could act — "
                "e.g. --dwell 60 --tick 5"
            )
        if self.flip_margin <= 1.0:
            raise ValueError(
                f"flip-margin must be > 1 (it is a ratio), got "
                f"{self.flip_margin} — e.g. --flip-margin 2"
            )
        if self.min_backends < 1:
            raise ValueError(
                f"min-backends must be >= 1, got {self.min_backends}"
            )


def check_policy(policy_kw: Optional[dict] = None,
                 standby: Optional[str] = None,
                 envelope: Optional[str] = None) -> tuple:
    """The ``fleet autoscale --check`` gate: validate the policy flags
    (watermarks ordered, dwell > tick), the standby roster syntax, and
    the envelope spec — no network anywhere. Returns ``(ok, report)``
    where ``report["checks"]`` carries one row per validation with a
    one-line fix hint on failure (the hints are the ValueError texts
    the real constructors raise, so --check and runtime agree by
    construction)."""
    checks: List[dict] = []

    def _run(name: str, fn) -> None:
        try:
            detail = fn()
        except ValueError as e:
            checks.append({"check": name, "ok": False, "hint": str(e)})
        else:
            row = {"check": name, "ok": True}
            if detail:
                row.update(detail)
            checks.append(row)

    _run("policy", lambda: (
        AutoscalePolicy(**(policy_kw or {})) and None
    ))
    _run("standby", lambda: (
        {"standby": parse_fleet(standby)} if standby
        else {"standby": [], "note": "no standby pool — scaling off"}
    ))
    _run("envelope", lambda: (
        {"envelope": str(parse_envelope_spec(envelope))} if envelope
        else {"note": "no envelope — backfill pacing off"}
    ))
    ok = all(c["ok"] for c in checks)
    return ok, {"ok": ok, "checks": checks}


class AutoscaleController:
    """See module docstring. ``tick()`` is one synchronous decision
    round (what the unit tests drive, fake clock in hand); ``run()``
    notes ``begin``, ticks every ``policy.tick_s`` until ``stop()`` or
    ``max_ticks``, notes ``end``, and returns the report dict."""

    def __init__(
        self,
        admin: RouterAdmin,
        *,
        standby: Sequence[str] = (),
        policy: Optional[AutoscalePolicy] = None,
        envelope: Optional[Envelope] = None,
        make_backend=BackendClient,
        ready_timeout_s: float = 60.0,
        drain_timeout_s: float = 120.0,
        poll_s: float = 0.1,
        max_ticks: Optional[int] = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.admin = admin
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.standby = list(standby)
        self.envelope = envelope
        self.make_backend = make_backend
        self.ready_timeout_s = float(ready_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.poll_s = float(poll_s)
        self.max_ticks = max_ticks
        self.clock = clock
        self.sleep = sleep
        self._stop = False
        # Standby addrs THIS controller activated — the only hosts
        # scale-down may ever park (the base fleet is the operator's).
        self._activated: set = set()
        self._last_action_ts: Optional[float] = None
        self._last_scale = 1.0       # last envelope scale pushed
        self._pushed_scale = False   # ever pushed at all
        self._last_attempts: Optional[int] = None  # disagg attempt total
        self.report: dict = {
            "status": "idle", "ticks": 0, "actions": [],
            "scale_ups": 0, "scale_downs": 0, "role_flips": 0,
            "failures": 0, "skipped_ticks": 0,
        }

    def stop(self) -> None:
        self._stop = True

    # ------------------------------------------------------ observation
    @staticmethod
    def _min_headroom(sloz: dict) -> Optional[float]:
        """Min per-tier SLO headroom, or None when no tier reports one
        (no SLO engine / no samples yet — the controller then neither
        scales up nor down: no evidence, no action)."""
        vals = []
        for doc in (sloz.get("tiers") or {}).values():
            h = doc.get("headroom")
            if isinstance(h, (int, float)):
                vals.append(float(h))
        return min(vals) if vals else None

    @staticmethod
    def _active_rows(statz: dict) -> List[dict]:
        """Fleet rows currently IN the serving set (anything not
        detached — draining/down hosts still count against pool size;
        they are not free capacity but they are not parked either)."""
        rows = (statz.get("fleet") or {}).get("backends") or []
        return [r for r in rows if r.get("status") != "detached"]

    @staticmethod
    def _row_load(row: dict) -> float:
        return (float(row.get("in_flight") or 0)
                + float(row.get("queue_depth") or 0))

    def _observe_envelope(self, statz: dict) -> Optional[float]:
        """The fleet's current envelope utilization: worst per-host
        HBM fraction (fleet rows) + the router-measured decode step
        time (its pooled latency window). None = scrape gap."""
        if self.envelope is None:
            return None
        hbm = None
        for r in self._active_rows(statz):
            v = r.get("hbm_frac_used")
            if isinstance(v, (int, float)):
                hbm = v if hbm is None else max(hbm, float(v))
        lat = statz.get("latency") or {}
        step_ms = None
        tps = lat.get("decode_tokens_per_s_p50")
        if isinstance(tps, (int, float)) and tps > 0:
            step_ms = 1000.0 / float(tps)
        return self.envelope.utilization(
            hbm_frac_used=hbm, step_ms_now=step_ms
        )

    # ------------------------------------------------------------ notes
    def _note(self, event: str, **fields) -> None:
        """Best-effort decision record on the router — a note that
        cannot land must not turn a healthy action into a failure."""
        try:
            self.admin.autoscale_note(event, **fields)
        except RolloutError:
            pass

    def _record(self, action: str, **fields) -> dict:
        entry = {"action": action, **fields}
        self.report["actions"].append(entry)
        # A long-lived daemon must not grow its report without bound
        # (a week of skipped ticks against a dead router is 100k+
        # entries) — keep the recent tail; the counters keep totals.
        if len(self.report["actions"]) > 512:
            del self.report["actions"][:-256]
        return entry

    # ------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One decision round; returns what happened ({"action": ...}).
        Never raises — an unobservable router is a skipped tick."""
        self.report["ticks"] += 1
        try:
            statz = self.admin.statz()
            sloz = self.admin.sloz()
        except RolloutError as e:
            self.report["skipped_ticks"] += 1
            return self._record("skip", error=str(e))
        # 1. Envelope pacing — independent of dwell: throttling batch
        # admission IS how the fleet avoids needing a pool action.
        self._tick_envelope(statz)
        pool = len(self._active_rows(statz))
        headroom = self._min_headroom(sloz)
        now = self.clock()
        if (self._last_action_ts is not None
                and now - self._last_action_ts < self.policy.dwell_s):
            return {"action": "dwell"}
        # 2. Scale within the hysteresis band.
        if headroom is not None and headroom < self.policy.low_headroom:
            addr = self._next_standby(statz)
            if addr is not None:
                return self._scale_up(addr, headroom, pool)
            return {"action": "hold", "why": "no standby left"}
        if headroom is not None and headroom > self.policy.high_headroom:
            addr = self._parkable(statz)
            if addr is not None:
                return self._scale_down(addr, headroom, pool)
        # 3. Rebalance roles on the measured demand mix.
        return self._maybe_flip(statz, pool)

    def run(self) -> dict:
        """The daemon loop; returns the report. Raises
        :class:`AutoscaleError` only when the router is unreachable
        before anything started."""
        try:
            statz = self.admin.statz()
        except RolloutError as e:
            raise AutoscaleError(
                f"router unreachable before the first tick: {e}"
            ) from e
        pool = len(self._active_rows(statz))
        self.report["status"] = "running"
        self._note("begin", standby=list(self.standby), pool=pool)
        ticks = 0
        while not self._stop:
            if self.max_ticks is not None and ticks >= self.max_ticks:
                break
            self.tick()
            ticks += 1
            if self._stop or (self.max_ticks is not None
                              and ticks >= self.max_ticks):
                break
            self.sleep(self.policy.tick_s)
        self.report["status"] = "stopped"
        self._note("end", pool=self._pool_now())
        return dict(self.report)

    def _pool_now(self) -> Optional[int]:
        try:
            return len(self._active_rows(self.admin.statz()))
        except RolloutError:
            return None

    # -------------------------------------------------------- envelope
    def _tick_envelope(self, statz: dict) -> None:
        util = self._observe_envelope(statz)
        if util is None:
            # Scrape gap (or no envelope declared): hold the last
            # pushed scale — flapping the throttle on missing data is
            # worse than a stale throttle.
            return
        scale = self.envelope.admission_fraction(util)
        moved = abs(scale - self._last_scale) >= 0.05
        if not moved and self._pushed_scale:
            return
        if not moved and scale >= 1.0:
            # Never pushed and nothing to throttle: stay silent.
            return
        try:
            self.admin.set_envelope(scale, util=util)
        except RolloutError as e:
            self.report["failures"] += 1
            self._record("envelope_failed", error=str(e))
            return
        self._last_scale = scale
        self._pushed_scale = True
        self._record("envelope", scale=round(scale, 4),
                     util=round(util, 4))
        self._note("envelope", scale=round(scale, 4),
                   util=round(util, 4))

    # ------------------------------------------------------------ scale
    def _next_standby(self, statz: dict) -> Optional[str]:
        """The next standby addr NOT currently in the active set."""
        active = {r.get("backend") for r in self._active_rows(statz)}
        for addr in self.standby:
            if addr not in active:
                return addr
        return None

    def _parkable(self, statz: dict) -> Optional[str]:
        """The emptiest ACTIVATED standby still in the active set —
        never a base-fleet host, never below ``min_backends``."""
        rows = self._active_rows(statz)
        if len(rows) <= self.policy.min_backends:
            return None
        mine = [r for r in rows if r.get("backend") in self._activated]
        if not mine:
            return None
        mine.sort(key=self._row_load)
        return mine[0].get("backend")

    def _scale_up(self, addr: str, headroom: float, pool: int) -> dict:
        b = self.make_backend(addr)
        try:
            # The bootstrap readiness gate, with the controller's own
            # clock — a standby that never answers /healthz within the
            # budget leaves the pool unchanged.
            wait_ready(
                [b], timeout_s=self.ready_timeout_s,
                poll_s=max(self.poll_s, 0.05),
                sleep=self.sleep, clock=self.clock,
            )
            out = self.admin.attach(addr)
        except (RuntimeError, RolloutError, BackendError) as e:
            # RolloutError is a RuntimeError subclass in spirit but
            # listed explicitly; either way: nothing changed, retry
            # next tick.
            self.report["failures"] += 1
            self._note("scale_up_failed", backend=addr, error=str(e),
                       headroom=round(headroom, 4), pool=pool)
            return self._record("scale_up_failed", backend=addr,
                                error=str(e))
        self._activated.add(addr)
        self._last_action_ts = self.clock()
        self.report["scale_ups"] += 1
        self._note("scale_up", backend=addr, pool=pool + 1,
                   headroom=round(headroom, 4),
                   warmed_chains=out.get("warmed_chains"))
        return self._record("scale_up", backend=addr,
                            warmed_chains=out.get("warmed_chains"))

    def _scale_down(self, addr: str, headroom: float, pool: int) -> dict:
        try:
            self.admin.park(addr)
        except RolloutError as e:
            self.report["failures"] += 1
            return self._record("scale_down_failed", backend=addr,
                                error=str(e))
        self._last_action_ts = self.clock()
        self.report["scale_downs"] += 1
        self._note("scale_down", backend=addr, pool=pool - 1,
                   headroom=round(headroom, 4))
        return self._record("scale_down", backend=addr)

    # ------------------------------------------------------- role flips
    def _maybe_flip(self, statz: dict, pool: int) -> dict:
        """Flip one host when the measured demand mix has shifted past
        the margin. Inputs: per-role load averages over the active
        rows, and the per-tick delta of disagg handoff ATTEMPTS (ok +
        failed + breakeven_loss, summed off the per-host fleet-row
        counts) — attempts flowing means prefill capacity is being
        consumed; a flat line means the prefill hosts are stranded
        capital."""
        rows = self._active_rows(statz)
        pre = [r for r in rows if r.get("role") == "prefill"]
        dec = [r for r in rows if r.get("role") in ("decode", "both")]
        attempts = 0
        for r in rows:
            for n in (r.get("disagg") or {}).values():
                attempts += int(n or 0)
        delta = (attempts - self._last_attempts
                 if self._last_attempts is not None else None)
        self._last_attempts = attempts
        if delta is None:
            return {"action": "hold", "why": "first mix sample"}

        def avg(group):
            return (sum(self._row_load(r) for r in group) / len(group)
                    if group else 0.0)

        pre_load, dec_load = avg(pre), avg(dec)
        m = self.policy.flip_margin
        # Decode-heavy shift: prefill hosts idle (no handoff attempts
        # this tick) while decode hosts queue — flip the emptiest
        # prefill host to decode. Guarded so the LAST prefill host only
        # flips when handoffs have genuinely stopped.
        if (pre and dec and delta == 0 and dec_load >= 1.0
                and dec_load > m * max(pre_load, 0.5)):
            target = min(pre, key=self._row_load)
            return self._flip(target["backend"], "decode", pool,
                              pre_load=pre_load, dec_load=dec_load)
        # Prefill-heavy shift: handoffs flowing and the prefill side
        # drowning while decode idles — flip the emptiest decode-side
        # host to prefill (never below min_backends decode/both hosts:
        # decode capacity serves ALL traffic, prefill only offloads).
        if (dec and len(dec) > self.policy.min_backends and delta
                and delta > 0 and pre_load >= 1.0
                and pre_load > m * max(dec_load, 0.5)):
            target = min(dec, key=self._row_load)
            return self._flip(target["backend"], "prefill", pool,
                              pre_load=pre_load, dec_load=dec_load)
        return {"action": "hold"}

    def _flip(self, addr: str, new_role: str, pool: int, **mix) -> dict:
        """drain -> idle-gate -> /rolez -> readiness-gate -> resume.
        Any failure before the flip resumes the host in its OLD role
        and retries a later tick; a failure AFTER the flip (resume or
        readiness lost) is recorded with ``flipped=true``."""
        was = None
        try:
            was = self.admin.fleet_row(addr).get("role")
            self.admin.drain(addr)
        except RolloutError as e:
            self.report["failures"] += 1
            self._note("role_flip_failed", backend=addr, role=new_role,
                       error=str(e), pool=pool)
            return self._record("role_flip_failed", backend=addr,
                                error=str(e))
        deadline = self.clock() + self.drain_timeout_s
        flipped = False
        try:
            while True:
                row = self.admin.fleet_row(addr)
                if int(row.get("in_flight") or 0) == 0:
                    break
                if self.clock() >= deadline:
                    raise AutoscaleError(
                        f"drain of {addr} still has "
                        f"{row.get('in_flight')} in-flight after "
                        f"{self.drain_timeout_s:g}s"
                    )
                self.sleep(self.poll_s)
            b = self.make_backend(addr)
            b.rolez(new_role)
            flipped = True
            # Readiness gate: the host must advertise the NEW role on
            # /healthz before traffic returns to it.
            gate = self.clock() + self.ready_timeout_s
            while True:
                try:
                    doc = b.probe()
                except BackendError as e:
                    doc = None
                    err = e
                if doc is not None and doc.get("role") == new_role:
                    break
                if self.clock() >= gate:
                    raise AutoscaleError(
                        f"{addr} never advertised role {new_role!r} "
                        f"within {self.ready_timeout_s:g}s"
                        + (f" (last probe error: {err})"
                           if doc is None else "")
                    )
                self.sleep(self.poll_s)
            self.admin.resume(addr)
        except (AutoscaleError, RolloutError, BackendError) as e:
            self.report["failures"] += 1
            if not flipped:
                # Nothing changed on the host — put it back to work in
                # its old role and retry a later tick.
                try:
                    self.admin.resume(addr)
                except RolloutError:
                    pass
            self._note("role_flip_failed", backend=addr, role=new_role,
                       was=was, error=str(e), flipped=flipped,
                       pool=pool)
            return self._record("role_flip_failed", backend=addr,
                                error=str(e), flipped=flipped)
        self._last_action_ts = self.clock()
        self.report["role_flips"] += 1
        self._note("role_flip", backend=addr, role=new_role, was=was,
                   pool=pool, **{k: round(v, 3) for k, v in mix.items()})
        return self._record("role_flip", backend=addr, role=new_role,
                            was=was)
