"""Multi-host serving fleet: HTTP-federated router over engine servers.

Training crosses hosts through ``parallel/distributed.py`` (JAX's GRPC
coordination service + DCN collectives); serving crosses hosts HERE,
through the engine HTTP/SSE protocol — the already-hardened,
hardware-agnostic surface every per-host server speaks (infer/server.py).
One router process federates N backend hosts behind the SAME server
front-end, so clients, the obs stack, and the CLI see one engine:

``backend``    a client for ONE remote engine host: submit + SSE
               stream pass-through, /healthz + /metrics scrape,
               per-call timeouts, capped exponential backoff with
               jitter, a shared retry budget, and a circuit breaker
               (trips on consecutive failures, half-opens on probe).
``router``     :class:`FleetRouter` — speaks the explicit
               ``ENGINE_INTERFACE`` contract (plus pooled
               ``counters()``/``latency_stats()``), so
               ``infer/server.py`` fronts a fleet unchanged:
               least-loaded routing, automatic resubmission of queued
               (not-yet-streamed) requests when a backend dies, and
               graceful draining via ``POST /drainz``.
``bootstrap``  the serving analogue of ``parallel/distributed.py``:
               host roster from ``--fleet host:port,...`` / the
               ``SHIFU_FLEET`` env var, readiness gating on each
               backend's ``/healthz``, and a periodic re-probe loop
               (failure-backoff per host, half-open trials on
               schedule) that brings dead backends back
               (``backend_up`` / ``backend_down`` flight events).
``rollout``    zero-downtime rolling weight rollout
               (``shifu_tpu fleet rollout --ckpt ...``): drain one
               ``--max-unavailable`` wave at a time, hot-swap weights
               via ``POST /reloadz`` (manifest-verified checkpoints —
               a torn artifact is refused with the old weights still
               serving), readiness-gate, resume — with the SLO
               watchdog's pooled p99 budgets as an automatic brake
               and ``--abort-on-slo`` rollback.
``chaos``      first-class fault injection: the ``FLEET_BACKEND_FAULT_*``
               server-side hooks the two-process tests drive
               (drop-nth, slow probes, reload failures, kill-after-N
               schedules) and the scheduled :class:`ChaosTrack` the
               loadgen harness folds into a scenario timeline
               (SIGKILL / drain / resume / mid-run rollout).

See docs/architecture.md ("The serving fleet") for the design and the
failure model, and README.md for the serving-topology ladder
(``tp`` -> ``dp x tp`` -> fleet of hosts).
"""

from shifu_tpu.fleet.backend import (
    BackendClient,
    BackendConfig,
    BackendError,
    CircuitBreaker,
    FleetUnavailable,
    RetryPolicy,
)
from shifu_tpu.fleet.chaos import (
    ChaosEvent,
    ChaosTrack,
    FaultSpec,
    faults_from_env,
    install_fault_hooks,
    parse_chaos_events,
)
from shifu_tpu.fleet.router import FleetRouter
from shifu_tpu.fleet.bootstrap import (
    FleetProber,
    build_fleet,
    parse_fleet,
    wait_ready,
)
from shifu_tpu.fleet.rollout import (
    RolloutController,
    RolloutError,
    RouterAdmin,
)

__all__ = [
    "BackendClient",
    "BackendConfig",
    "BackendError",
    "ChaosEvent",
    "ChaosTrack",
    "CircuitBreaker",
    "FaultSpec",
    "FleetProber",
    "FleetRouter",
    "FleetUnavailable",
    "RetryPolicy",
    "RolloutController",
    "RolloutError",
    "RouterAdmin",
    "build_fleet",
    "faults_from_env",
    "install_fault_hooks",
    "parse_chaos_events",
    "parse_fleet",
    "wait_ready",
]
