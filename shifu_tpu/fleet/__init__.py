"""Multi-host serving fleet: HTTP-federated router over engine servers.

Training crosses hosts through ``parallel/distributed.py`` (JAX's GRPC
coordination service + DCN collectives); serving crosses hosts HERE,
through the engine HTTP/SSE protocol — the already-hardened,
hardware-agnostic surface every per-host server speaks (infer/server.py).
One router process federates N backend hosts behind the SAME server
front-end, so clients, the obs stack, and the CLI see one engine:

``backend``    a client for ONE remote engine host: submit + SSE
               stream pass-through, /healthz + /metrics scrape,
               per-call timeouts, capped exponential backoff with
               jitter, a shared retry budget, and a circuit breaker
               (trips on consecutive failures, half-opens on probe).
``router``     :class:`FleetRouter` — speaks the explicit
               ``ENGINE_INTERFACE`` contract (plus pooled
               ``counters()``/``latency_stats()``), so
               ``infer/server.py`` fronts a fleet unchanged:
               least-loaded routing, automatic resubmission of queued
               (not-yet-streamed) requests when a backend dies, and
               graceful draining via ``POST /drainz``.
``bootstrap``  the serving analogue of ``parallel/distributed.py``:
               host roster from ``--fleet host:port,...`` / the
               ``SHIFU_FLEET`` env var, readiness gating on each
               backend's ``/healthz``, and a periodic re-probe loop
               (failure-backoff per host, half-open trials on
               schedule) that brings dead backends back
               (``backend_up`` / ``backend_down`` flight events).
``rollout``    zero-downtime rolling weight rollout
               (``shifu_tpu fleet rollout --ckpt ...``): drain one
               ``--max-unavailable`` wave at a time, hot-swap weights
               via ``POST /reloadz`` (manifest-verified checkpoints —
               a torn artifact is refused with the old weights still
               serving), readiness-gate, resume — with the SLO
               watchdog's pooled p99 budgets as an automatic brake
               and ``--abort-on-slo`` rollback.
``chaos``      first-class fault injection: the ``FLEET_BACKEND_FAULT_*``
               server-side hooks the two-process tests drive
               (drop-nth, slow probes, reload failures, kill-after-N
               schedules) and the scheduled :class:`ChaosTrack` the
               loadgen harness folds into a scenario timeline
               (SIGKILL / drain / resume / mid-run rollout).
``autoscale``  the elastic fleet control plane
               (``shifu_tpu fleet autoscale``): a control-loop daemon
               over ``/sloz`` + ``/statz`` that activates/parks
               standby hosts on SLO-headroom hysteresis bands,
               rebalances prefill/decode roles on the measured demand
               mix (drain -> ``POST /rolez`` -> resume), and paces
               batch backfill against the declared ``envelope``
               budget — every decision noted on the router, every
               actuator failure degrading to "retry next tick".
``envelope``   the declarative serving envelope the controller paces
               against: HBM high-water fraction + a step-time power
               proxy folded into one batch-admission scale.

See docs/architecture.md ("The serving fleet") for the design and the
failure model, and README.md for the serving-topology ladder
(``tp`` -> ``dp x tp`` -> fleet of hosts).
"""

from shifu_tpu.fleet.backend import (
    BackendClient,
    BackendConfig,
    BackendError,
    CircuitBreaker,
    FleetUnavailable,
    RetryPolicy,
)
from shifu_tpu.fleet.chaos import (
    ChaosEvent,
    ChaosTrack,
    FaultSpec,
    faults_from_env,
    install_fault_hooks,
    parse_chaos_events,
)
from shifu_tpu.fleet.router import FleetRouter
from shifu_tpu.fleet.bootstrap import (
    FleetProber,
    build_fleet,
    parse_fleet,
    wait_ready,
)
from shifu_tpu.fleet.rollout import (
    RolloutController,
    RolloutError,
    RouterAdmin,
)
from shifu_tpu.fleet.autoscale import (
    AutoscaleController,
    AutoscaleError,
    AutoscalePolicy,
    check_policy,
)
from shifu_tpu.fleet.envelope import Envelope, parse_envelope_spec

__all__ = [
    "AutoscaleController",
    "AutoscaleError",
    "AutoscalePolicy",
    "BackendClient",
    "BackendConfig",
    "BackendError",
    "ChaosEvent",
    "ChaosTrack",
    "CircuitBreaker",
    "Envelope",
    "FaultSpec",
    "FleetProber",
    "FleetRouter",
    "FleetUnavailable",
    "RetryPolicy",
    "RolloutController",
    "RolloutError",
    "RouterAdmin",
    "build_fleet",
    "check_policy",
    "faults_from_env",
    "install_fault_hooks",
    "parse_chaos_events",
    "parse_envelope_spec",
    "parse_fleet",
    "wait_ready",
]
