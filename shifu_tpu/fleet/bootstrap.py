"""Fleet bootstrap: the serving analogue of ``parallel/distributed.py``.

Training brings hosts together through JAX's GRPC coordination service
and then speaks XLA collectives over DCN; serving brings hosts together
HERE and then speaks the engine HTTP protocol over the same network.
The pieces mirror ``distributed.initialize``'s job:

  * :func:`parse_fleet` — the host roster, from ``--fleet
    host:port,...`` or the ``SHIFU_FLEET`` environment variable (flag
    wins; the env var is the k8s-style deployment path where every
    router pod gets the roster injected).
  * :func:`wait_ready` — readiness gating: poll each backend's
    ``/healthz`` until it answers (and fetch ``max_len`` from
    ``/v1/models``), with a deadline. By default the fleet starts when
    ANY backend is ready — the prober brings stragglers in later —
    mirroring how a pod job tolerates a slow host at startup.
  * :class:`FleetProber` — the periodic re-probe loop: backends that
    are dead (breaker open) or never answered get re-probed every
    ``interval_s``; a success closes the breaker (``backend_up``
    flight event via the breaker's transition hook) and refreshes the
    cached health document the router's load balancing reads.
  * :func:`build_fleet` — roster -> gated -> probed
    :class:`~shifu_tpu.fleet.router.FleetRouter` with the prober
    running, the one-call path ``serve --fleet`` uses.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

from shifu_tpu.fleet.backend import BackendClient, BackendConfig, BackendError
from shifu_tpu.fleet.router import FleetRouter

FLEET_ENV = "SHIFU_FLEET"


def parse_fleet(spec: Optional[str] = None, *, env=None) -> List[str]:
    """``"host:port,host:port"`` -> validated address list. ``spec``
    (the ``--fleet`` flag) wins; otherwise the ``SHIFU_FLEET`` env var.
    Raises ValueError on an empty/absent roster or malformed entries —
    a fleet router with no roster is a misconfiguration, not a
    default."""
    if spec is None:
        spec = (env if env is not None else os.environ).get(FLEET_ENV)
    if not spec or not str(spec).strip():
        raise ValueError(
            "no fleet roster: pass --fleet host:port,... or set "
            f"{FLEET_ENV}"
        )
    addrs = [a.strip() for a in str(spec).split(",") if a.strip()]
    if not addrs:
        raise ValueError(f"fleet roster {spec!r} parsed to no backends")
    seen = set()
    for a in addrs:
        host, sep, port = a.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(f"fleet entry {a!r} is not host:port")
        if a in seen:
            raise ValueError(f"duplicate fleet entry {a!r}")
        seen.add(a)
    return addrs


def wait_ready(
    backends: Sequence[BackendClient], *, timeout_s: float = 60.0,
    poll_s: float = 0.5, require_all: bool = False,
    sleep=time.sleep, clock=time.monotonic,
) -> Tuple[List[BackendClient], List[BackendClient]]:
    """Gate on each backend's ``/healthz`` answering; fetch its
    ``/v1/models`` (for ``max_len``) on first success. Returns
    ``(ready, not_ready)``; raises RuntimeError when the deadline
    passes with nothing ready (or, under ``require_all``, with anyone
    missing). Clock/sleep injectable for tests."""
    ready: List[BackendClient] = []
    pending = list(backends)
    deadline = clock() + timeout_s
    while pending:
        still = []
        for b in pending:
            try:
                b.probe()
                try:
                    b.models()
                except BackendError:
                    pass  # healthz answered; max_len stays unknown
                ready.append(b)
            except BackendError:
                still.append(b)
        pending = still
        if not pending:
            break
        if clock() >= deadline:
            missing = [b.addr for b in pending]
            if require_all or not ready:
                raise RuntimeError(
                    f"fleet readiness gate failed after {timeout_s:g}s: "
                    f"not ready: {missing}"
                    + ("" if ready else " (no backend ready at all)")
                )
            break
        sleep(poll_s)
    return ready, pending


class FleetProber(threading.Thread):
    """Periodic re-probe of dead/unknown backends (daemon thread).

    Healthy backends are probed every ``interval_s`` so the cached
    queue-depth/health the router balances on stays fresh. A backend
    whose probes keep FAILING backs off instead of being hammered every
    interval — consecutive failures double its personal probe interval
    up to ``backoff_max_mult``× (a long-dead host in a 2 s-interval
    fleet costs one timed-out connect every 16 s, not every 2) — with
    one deliberate exception: when the backend's circuit breaker has
    finished its cooldown, the probe fires ON SCHEDULE regardless of
    backoff, because that probe IS the breaker's half-open trial and
    delaying it would delay the host's re-admission
    (``CircuitBreaker.cooldown_remaining``; fake-clock-tested in
    tests/test_rollout.py). A success resets the backoff, and the
    recovered host rejoins the rotation within one interval
    (``backend_up`` flight event).

    ``tick()`` is one synchronous pass (clock-injectable — tests drive
    the whole backoff walk without a thread or a sleep); ``run()`` just
    calls it every ``interval_s``."""

    def __init__(self, router: FleetRouter, *, interval_s: float = 2.0,
                 backoff_max_mult: int = 8, clock=time.monotonic):
        super().__init__(name="shifu-fleet-prober", daemon=True)
        self.router = router
        self.interval_s = float(interval_s)
        self.backoff_max_mult = max(1, int(backoff_max_mult))
        self._clock = clock
        self._fails: dict = {}      # addr -> consecutive probe failures
        self._next_due: dict = {}   # addr -> earliest next probe time
        self._stop_ev = threading.Event()

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop_ev.set()
        if self.is_alive():
            self.join(join_timeout_s)

    def backoff_mult(self, addr: str) -> int:
        """The current interval multiplier for ``addr`` (1 = healthy
        cadence; doubles per consecutive failure, capped)."""
        return min(
            2 ** self._fails.get(addr, 0), self.backoff_max_mult
        )

    def _due(self, b, now: float) -> bool:
        if now >= self._next_due.get(b.addr, 0.0):
            return True
        # Backed off, but the breaker's half-open trial is due: probe
        # anyway — backoff must never postpone re-admission.
        from shifu_tpu.fleet.backend import CircuitBreaker

        return (
            b.breaker.state == CircuitBreaker.OPEN
            and b.breaker.cooldown_remaining() <= 0.0
        )

    def tick(self) -> None:
        """One probe pass over the roster (skips detached backends and
        ones still inside their personal backoff window)."""
        now = self._clock()
        for b in self.router.backends:
            if self._stop_ev.is_set():
                return
            if b.detached or not self._due(b, now):
                continue
            try:
                self.router.probe_backend(b)
            except BackendError:
                self._fails[b.addr] = self._fails.get(b.addr, 0) + 1
                self._next_due[b.addr] = (
                    now + self.interval_s * self.backoff_mult(b.addr)
                )
                continue
            self._fails[b.addr] = 0
            self._next_due[b.addr] = now + self.interval_s
            # Refresh /v1/models alongside /healthz: model ids and the
            # served-ckpt field change underneath a live router (weight
            # rollouts, operators repointing a host), and model-aware
            # routing + the /statz roster must track them.
            try:
                b.models()
            except BackendError:
                pass  # healthz answered; models stay stale
            # ...and /cachez: the sticky router scores hosts by cache
            # pressure and gates migration on the host tier, both read
            # from this cached doc — never a per-request scrape.
            b.refresh_cachez()
        # With every due backend's digest advertisement fresh, warm
        # any stone-cold joiner from its peers (a no-op almost every
        # tick: each backend is bulk-warmed at most once).
        warm = getattr(self.router, "maybe_peer_warm", None)
        if warm is not None and not self._stop_ev.is_set():
            try:
                warm()
            except Exception:  # noqa: BLE001 — warming is best-effort
                pass

    def run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            self.tick()


def build_fleet(
    spec: Optional[str] = None, *,
    cfg: Optional[BackendConfig] = None,
    metrics=None, flight=None,
    ready_timeout_s: float = 60.0, require_all: bool = False,
    probe_interval_s: float = 2.0, start_prober: bool = True,
    **router_kw,
) -> FleetRouter:
    """Roster -> readiness-gated :class:`FleetRouter` with the re-probe
    loop running (``router.prober``; ``prober.stop()`` on shutdown).
    The one-call construction path ``serve --fleet`` uses."""
    addrs = parse_fleet(spec)
    backends = [BackendClient(a, cfg) for a in addrs]
    wait_ready(
        backends, timeout_s=ready_timeout_s, require_all=require_all
    )
    router = FleetRouter(
        backends, metrics=metrics, flight=flight, **router_kw
    )
    # One probe pass THROUGH the router before the prober's first
    # interval: wait_ready probed via the raw clients, so the router's
    # clock-offset estimator (trace alignment) and probe-latency
    # histogram would otherwise stay empty until probe_interval_s in.
    for b in backends:
        try:
            router.probe_backend(b)
        except BackendError:
            pass  # the prober keeps retrying dead hosts
        b.refresh_cachez()  # seed the sticky score's cache signal
    # The probes above also cached each backend's disaggregation role
    # (the /healthz + /v1/models "role" field — serve --role). Record
    # a disaggregated topology once so the flight ring says which
    # hosts are prefill/decode; the prober keeps the roles fresh the
    # same way it keeps model ids fresh.
    roles = {b.addr: FleetRouter._role(b) for b in backends}
    if any(r != "both" for r in roles.values()):
        router.flight.record("fleet_roles", roles=roles)
    # Cold hosts joining a fleet that already holds shared prefixes
    # warm from their peers NOW, not a prober interval later — a
    # freshly autoscaled backend's first request should prefill warm.
    try:
        router.maybe_peer_warm()
    except Exception:  # noqa: BLE001 — warming is best-effort
        pass
    prober = FleetProber(router, interval_s=probe_interval_s)
    router.prober = prober
    if start_prober:
        prober.start()
    return router
