"""FleetRouter: N remote engine hosts behind one ENGINE_INTERFACE.

The router IS an "engine" to the serving front-end — it provides every
``ENGINE_INTERFACE`` name (infer/engine.py), so ``infer/server.py``
fronts a fleet unchanged: the same ``EngineRunner`` thread drives it,
the same /healthz//statz//metrics//debugz endpoints serve it, and the
same SLO watchdog budgets apply (fed by the router's POOLED latency
window). Where ``ReplicatedEngine`` routes over in-process engines
sharing one device pool, ``FleetRouter`` routes over HTTP backends —
the submit/stream/cancel surface is identical by construction.

Mechanics:

  * ``submit()`` (engine thread) picks the least-loaded routable
    backend — live router-local ``in_flight`` first, then the remote
    queue depth from the last probe, then lowest index — and hands the
    request to a per-request worker thread. No HTTP happens on the
    engine thread.
  * The worker POSTs ``stream: true`` to the backend and feeds the
    request's ``generated``/``logprobs`` lists as SSE deltas arrive
    (the server's ``live_requests()`` diffing streams them onward).
    On failure BEFORE the first delta the request is still invisible
    to the client, so the worker resubmits it to another backend
    (breaker bookkeeping + retry budget + capped jittered backoff);
    after first delta a failure is surfaced — the client already holds
    tokens the fleet cannot un-send.
  * ``cancel()`` closes the worker's backend connection; the backend
    server frees the remote slot on disconnect (its documented
    disconnect-cancel path), so a client disconnect at the ROUTER
    propagates all the way to the remote engine.
  * ``drain(addr)`` (the ``POST /drainz`` admin verb) stops routing
    new work to a backend, lets in-flight streams finish, then
    detaches it (``backend_draining``/``backend_detached`` flight
    events; re-attach by restarting the router with it in the roster).

Sticky, cache-aware sessions (ROADMAP item 1, this round): every
routed prompt is keyed by the SAME sha256 prefix-chain digest scheme
the engines' prefix caches use (``infer/kvtier.chain_keys``), and a
bounded LRU affinity table remembers which backend served each chain.
A follow-up turn (its prompt extends the chain) routes back to that
host — where the prefix cache makes its prefill nearly free — and the
load score every pick uses folds in per-backend prefix-cache occupancy
from the prober's ``/cachez`` scrape. When the sticky host is hot,
draining (``/drainz``), or mid-rollout, the session MIGRATES: the
router fetches the host's exported KV chain (``GET /kv/pages``, the
PR-11 transfer) and ingests it into the new host before routing the
turn there — gated by the same measured migrate-vs-cold-prefill
breakeven EMAs the disaggregated path uses (unmeasured -> explore,
loss -> counted cold prefill). ``shifu_session_*``/``shifu_migrate_*``
families + ``kv_migrate`` spans under the caller's trace_id record
every decision.

Observability: ``shifu_fleet_*`` registry families (per-backend
requests/retries/failures counters, breaker-state/up/in-flight gauges,
request + probe latency histograms), ``backend_down``/``backend_up``
flight events, a per-backend block on ``/statz`` (via
``fleet_stats()``), and ``health_reasons()`` naming dead backends so
the router's ``/healthz`` reports ``degraded`` while part of the fleet
is down.
"""

from __future__ import annotations

import collections
import math
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from shifu_tpu.obs import disttrace as _dtrace

from shifu_tpu.fleet.backend import (
    BackendClient,
    BackendConfig,
    BackendError,
    CircuitBreaker,
    FleetUnavailable,
    RetryPolicy,
)
from shifu_tpu.infer.engine import (
    Completion,
    LiveRequest,
    UnknownModelError,
)
from shifu_tpu.infer.kvtier import chain_keys
from shifu_tpu.infer.sampling import SampleConfig

_SAMPLING_FIELDS = (
    "temperature", "top_k", "top_p", "min_p",
    "presence_penalty", "frequency_penalty", "repetition_penalty",
)


class _FleetRequest:
    """One routed request's life: wire body, live token lists (the
    streaming surface aliases these), cancel flag, and the stream the
    worker currently holds (closed to cancel remotely)."""

    def __init__(self, rid: int, body: dict, model: Optional[str] = None,
                 tier: str = "interactive", trace=None):
        self.rid = rid
        self.body = body
        self.model = model             # route only to backends serving it
        self.tier = tier               # admission tier (batch backfill)
        self.trace = trace             # TraceContext for this hop, if any
        self.generated: List[int] = []
        self.logprobs: List[float] = []
        self.streamed = False          # first delta arrived
        self.cancelled = False
        self.stream = None             # the live _SSEStream, if any
        self.backend: Optional[BackendClient] = None
        self.submitted = time.monotonic()
        self.first_tok_at: Optional[float] = None
        # Sticky-session state (FleetRouter._session_route /
        # _affinity_note): the prompt's prefix-chain keys, the table
        # key the lookup matched (superseded on completion), whether
        # the wire body carried kv_export, and the routing outcome —
        # recorded once per request, first placement wins.
        self.aff_keys: Optional[List[bytes]] = None
        self.aff_key: Optional[bytes] = None
        self.exported = False
        self.session_outcome: Optional[str] = None


class FleetRouter:
    """Route requests over remote engine-server ``backends``.

    ``backends`` — :class:`BackendClient` list (build via
    ``fleet.bootstrap.build_fleet`` for roster parsing + readiness
    gating + the re-probe loop). ``metrics``/``flight`` default to the
    process-global sinks like every engine. ``policy`` is the shared
    retry budget/backoff; ``sleep`` is injectable so retry tests run
    without wall-clock waits.

    Sampling note: per-request sampling fields resolve against
    :attr:`sample_cfg` (a default :class:`SampleConfig`) at the
    router's front-end before they reach the wire — a request that
    sets ANY sampling field therefore sends the full resolved set to
    the backend. Requests with no sampling fields inherit the
    BACKEND's configured sampling, exactly like a direct client.
    """

    def __init__(self, backends: List[BackendClient], *,
                 policy: Optional[RetryPolicy] = None,
                 metrics=None, flight=None,
                 step_wait_s: float = 0.02,
                 drain_poll_s: float = 0.05,
                 disagg_min_prompt: int = 64,
                 sticky_sessions: bool = True,
                 affinity_page: int = 32,
                 affinity_slots: int = 2048,
                 sticky_hot_gap: int = 4,
                 cache_weight: float = 1.0,
                 sleep=time.sleep):
        if not backends:
            raise ValueError("need at least one fleet backend")
        if int(affinity_page) < 1:
            raise ValueError(
                f"affinity_page must be >= 1, got {affinity_page}"
            )
        if int(affinity_slots) < 1:
            raise ValueError(
                f"affinity_slots must be >= 1, got {affinity_slots}"
            )
        if float(cache_weight) < 0.0:
            raise ValueError(
                f"cache_weight must be >= 0, got {cache_weight}"
            )
        from shifu_tpu import obs as _obs

        self.backends = list(backends)
        addrs = [b.addr for b in self.backends]
        if len(set(addrs)) != len(addrs):
            raise ValueError(f"duplicate backend addresses: {addrs}")
        self.policy = policy if policy is not None else RetryPolicy()
        self.metrics = metrics if metrics is not None else _obs.REGISTRY
        self.flight = flight if flight is not None else _obs.FLIGHT
        self._sleep = sleep
        self._step_wait_s = float(step_wait_s)
        self._drain_poll_s = float(drain_poll_s)
        self._lock = threading.Lock()
        self._rid = 0
        self._reqs: Dict[int, _FleetRequest] = {}
        self._done: collections.deque = collections.deque()
        self._failures: Dict[int, Exception] = {}
        self._progress = threading.Event()
        self._trace_window: collections.deque = collections.deque(maxlen=256)
        self._trace_lock = threading.Lock()
        self.resubmissions = 0
        self.requests_completed = 0
        self.tokens_generated = 0
        self.cancellations = 0
        self.batch_completed = 0  # batch-tier completions (SLO-exempt)

        # Prefill/decode disaggregation. Prompts at/above
        # ``disagg_min_prompt`` tokens are candidates for the two-host
        # path (prefill host -> SKVP page transfer -> decode host) when
        # the roster has a prefill-role backend. The migrate-vs-cold-
        # prefill breakeven is MEASURED, not assumed: transfer
        # bytes/ms + bytes/token EMAs (alpha 0.2, the kvtier.py
        # pattern) against the decode host's own prefill tok/ms from
        # its last /healthz probe — unmeasured sides explore.
        self.disagg_min_prompt = int(disagg_min_prompt)
        self._xfer_bytes_per_ms: Optional[float] = None
        self._xfer_bytes_per_token: Optional[float] = None
        self.disagg_handoffs = 0          # handoffs that completed
        self.disagg_fallbacks = 0         # handoff failed -> colocated
        self.disagg_breakeven_losses = 0  # wire lost -> never attempted
        # Per-PREFILL-HOST handoff outcome counts ({addr: {outcome:
        # n}}), surfaced in the /statz fleet rows — the autoscale
        # rebalancer's demand-mix signal: a prefill host whose
        # attempts flatline while decode queues grow is a flip
        # candidate.
        self._disagg_by_host: Dict[str, Dict[str, int]] = {}

        # Sticky, cache-aware sessions. The affinity table maps the
        # DEEPEST full-page prefix-chain digest of a served prompt (the
        # kvtier.chain_keys scheme — ``affinity_page`` tokens per link,
        # salted by adapter exactly like the engines' prefix caches) to
        # the backend that served it, bounded-LRU at
        # ``affinity_slots``. A later turn extends the chain, so its
        # key list CONTAINS an earlier turn's deepest key — lookup
        # walks deepest-first and follows the session with no wire
        # session id at all. ``sticky_hot_gap`` is how much busier (in
        # in-flight + queued requests) the sticky host may be than the
        # best alternative before affinity yields; ``cache_weight`` is
        # how many queued requests one FULL prefix cache counts for in
        # the load score. ``sticky_sessions=False`` disables the whole
        # surface (the bench's blind-routing control).
        self.sticky_sessions = bool(sticky_sessions)
        self.affinity_page = int(affinity_page)
        self.affinity_slots = int(affinity_slots)
        self.sticky_hot_gap = int(sticky_hot_gap)
        self.cache_weight = float(cache_weight)
        self._affinity: "collections.OrderedDict[bytes, dict]" = (
            collections.OrderedDict()
        )
        self._affinity_lock = threading.Lock()
        self.session_counts = {
            "sticky": 0, "new": 0, "migrated": 0, "rebalanced": 0,
        }
        self.migrations = 0               # KV chains moved host-to-host
        self.migrate_fallbacks = 0        # transfer failed -> cold prefill
        self.migrate_breakeven_losses = 0  # wire lost -> cold prefill
        self.migrate_bytes = 0            # SKVP payload bytes moved

        # Fleet-wide content-addressed peer fetch (the tier-3 store):
        # each backend advertises its held chain digests in /cachez;
        # the router folds them into a fleet digest map and, before a
        # cold attempt, pulls the prompt's deepest held prefix from
        # whichever peer holds it via GET /kv/pages?digest=. Gated per
        # SOURCE by a measured fetch-bandwidth EMA against the
        # destination's own prefill rate — unmeasured sources explore.
        self.peer_fetches = 0             # fetch+ingest completed
        self.peer_failures = 0            # either leg errored -> cold
        self.peer_breakeven_losses = 0    # wire lost -> never attempted
        self.peer_pages = 0               # KV pages moved peer-to-peer
        self.peer_bytes = 0               # SKVP payload bytes moved
        self.peer_warmups = 0             # chains moved by cold-host warming
        self._peer_bw: Dict[str, float] = {}   # src addr -> bytes/ms EMA
        self._peer_lock = threading.Lock()
        self._peer_warmed: set = set()         # addrs already bulk-warmed
        self._peer_warm_strikes: Dict[str, int] = {}  # all-failed rounds
        self._digest_map: Dict[str, List[BackendClient]] = {}
        self._digest_map_sig = None

        # Distributed tracing (obs/disttrace.py): the router is a hop —
        # it records router_hop/resubmit spans in its own store, keyed
        # by a host label naming this process, and assembles fleet-wide
        # traces by pulling each backend's /tracez slice through the
        # per-backend clock offsets the prober measures.
        self.host_label = f"{socket.gethostname()}:{os.getpid()}"
        self.replica_label = "router"
        self._span_store = _dtrace.SpanStore()
        self._clock = _dtrace.ClockSync()
        self._fed_lock = threading.Lock()
        self._fed_pooled: Dict[tuple, float] = {}

        # ENGINE_INTERFACE identity/config surface. The router has no
        # local model — beam/embeddings need device access and 400
        # cleanly through the empty ``buckets`` tuple.
        self.model = None
        self.params = None
        self.tokenizer = None
        self.buckets = ()
        self.max_len = min(
            (b.max_len for b in self.backends if b.max_len), default=2048
        )
        self.eos_id = None
        self.sample_cfg = SampleConfig()
        self.per_request_sampling = True
        self.enable_penalties = True
        self.enable_logit_bias = True
        self.lora = None

        # shifu_fleet_* families (docs/observability.md).
        reg = self.metrics
        self._c_requests = reg.counter(
            "shifu_fleet_requests_total",
            "Requests routed to each backend (attempts, incl. retries "
            "that reached the wire)", labelnames=("backend",),
        )
        self._c_retries = reg.counter(
            "shifu_fleet_retries_total",
            "Failures at a backend that caused the request to retry",
            labelnames=("backend",),
        )
        self._c_failures = reg.counter(
            "shifu_fleet_failures_total",
            "Requests that FAILED at a backend (retried or not)",
            labelnames=("backend",),
        )
        self._g_breaker = reg.gauge(
            "shifu_fleet_breaker_state",
            "Circuit breaker per backend: 0 closed, 1 half-open, 2 open",
            labelnames=("backend",),
        )
        self._g_up = reg.gauge(
            "shifu_fleet_backend_up",
            "1 while the backend is routable (not down/draining/"
            "detached)", labelnames=("backend",),
        )
        self._g_inflight = reg.gauge(
            "shifu_fleet_in_flight",
            "Requests this router currently has running on the backend",
            labelnames=("backend",),
        )
        self._g_budget = reg.gauge(
            "shifu_fleet_retry_budget",
            "Remaining shared retry-budget tokens",
        ).labels()
        self._h_request = reg.histogram(
            "shifu_fleet_request_seconds",
            "Routed request wall time at the router (submit to final "
            "event)", labelnames=("backend",),
        )
        self._h_probe = reg.histogram(
            "shifu_fleet_probe_seconds",
            "Backend /healthz scrape latency", labelnames=("backend",),
        )
        # shifu_disagg_* family: handoff outcomes. All three labels are
        # pre-seeded so a scrape shows the zero rows before the first
        # disaggregated request.
        self._c_disagg = reg.counter(
            "shifu_disagg_handoffs_total",
            "Prefill->decode handoff attempts by outcome: ok "
            "(completed disaggregated), failed (fell back colocated), "
            "breakeven_loss (wire predicted slower than a cold "
            "prefill — never attempted)", labelnames=("outcome",),
        )
        for oc in ("ok", "failed", "breakeven_loss"):
            self._c_disagg.labels(outcome=oc)
        # shifu_session_* / shifu_migrate_* families: sticky-session
        # placement outcomes and live KV migrations. All labels
        # pre-seeded so scrapes show zero rows from the first request.
        self._c_session = reg.counter(
            "shifu_session_requests_total",
            "Routed requests by sticky-session placement outcome: "
            "sticky (affinity hit, served on the remembered host), new "
            "(no affinity entry matched the prompt's prefix chain), "
            "migrated (sticky host unavailable/hot — KV pages moved "
            "and the turn served warm elsewhere), rebalanced (moved "
            "hosts WITHOUT a migration — cold prefill)",
            labelnames=("outcome",),
        )
        for oc in ("sticky", "new", "migrated", "rebalanced"):
            self._c_session.labels(outcome=oc)
        self._g_affinity = reg.gauge(
            "shifu_session_affinity_entries",
            "Live session->backend affinity-table entries (bounded LRU "
            "at the router's affinity_slots)",
        ).labels()
        self._c_migrate = reg.counter(
            "shifu_migrate_total",
            "Session KV-migration attempts by outcome: ok (chain "
            "fetched from the sticky host and ingested into the new "
            "one), failed (either leg errored — fell back to cold "
            "prefill), breakeven_loss (wire predicted slower than the "
            "new host recomputing — never attempted)",
            labelnames=("outcome",),
        )
        for oc in ("ok", "failed", "breakeven_loss"):
            self._c_migrate.labels(outcome=oc)
        self._c_migrate_bytes = reg.counter(
            "shifu_migrate_bytes_total",
            "SKVP payload bytes moved by completed session migrations",
        ).labels()
        self._h_migrate = reg.histogram(
            "shifu_migrate_seconds",
            "Session KV-migration wall time (fetch + ingest, one "
            "timed unit — the breakeven EMAs' sample)",
        ).labels()
        # shifu_kv_peer_* family: content-addressed peer page fetches
        # (docs/observability.md). All labels pre-seeded.
        self._c_peer = reg.counter(
            "shifu_kv_peer_fetches_total",
            "Digest-keyed peer KV fetches by outcome: ok (chain "
            "fetched from the holder and ingested into the target), "
            "failed (either leg errored — the target prefills cold), "
            "breakeven_loss (the source's measured fetch bandwidth "
            "predicted slower than the target recomputing — never "
            "attempted)", labelnames=("outcome",),
        )
        for oc in ("ok", "failed", "breakeven_loss"):
            self._c_peer.labels(outcome=oc)
        self._c_peer_pages = reg.counter(
            "shifu_kv_peer_pages_total",
            "KV pages moved by completed peer fetches",
        ).labels()
        self._c_peer_bytes = reg.counter(
            "shifu_kv_peer_bytes_total",
            "SKVP payload bytes moved by completed peer fetches",
        ).labels()
        # shifu_rollout_* families: rolling-weight-rollout progress as
        # reported by the rollout controller via POST /rolloutz
        # (rollout_note). The controller may be a separate process —
        # these series live HERE so one /metrics scrape shows traffic
        # AND the rollout moving through it.
        self._c_rollout_events = reg.counter(
            "shifu_rollout_events_total",
            "Rollout lifecycle events recorded via /rolloutz",
            labelnames=("event",),
        )
        self._g_rollout_active = reg.gauge(
            "shifu_rollout_active",
            "1 while a rolling weight rollout is in progress "
            "(paused counts as in progress)",
        ).labels()
        self._g_rollout_updated = reg.gauge(
            "shifu_rollout_backends_updated",
            "Backends already serving the rollout's target checkpoint",
        ).labels()
        self._g_rollout_paused = reg.gauge(
            "shifu_rollout_paused",
            "1 while the rollout wave is paused on an SLO breach",
        ).labels()
        self._rollout: Optional[dict] = None  # /statz rollout block
        # shifu_autoscale_* / shifu_envelope_* families: elastic-fleet
        # control-plane decisions as reported by the autoscale
        # controller via POST /autoscalez (autoscale_note). Like the
        # rollout families, the controller may be a separate process —
        # the series live HERE so one /metrics scrape shows traffic
        # AND the fleet reshaping under it.
        self._c_autoscale_actions = reg.counter(
            "shifu_autoscale_actions_total",
            "Autoscale control-loop actions recorded via /autoscalez: "
            "scale_up (standby activated), scale_down (host parked), "
            "role_flip (drain-flip-resume completed), envelope "
            "(batch-admission scale pushed), scale_up_failed / "
            "role_flip_failed (actuator failure — fleet unchanged, "
            "retry next tick)", labelnames=("action",),
        )
        for ac in ("scale_up", "scale_down", "role_flip", "envelope",
                   "scale_up_failed", "role_flip_failed"):
            self._c_autoscale_actions.labels(action=ac)
        self._g_autoscale_active = reg.gauge(
            "shifu_autoscale_active",
            "1 while an autoscale controller is attached and ticking",
        ).labels()
        self._g_autoscale_pool = reg.gauge(
            "shifu_autoscale_pool_size",
            "Active serving-set size as the autoscale controller last "
            "counted it (attached, non-parked backends)",
        ).labels()
        self._c_role_flips = reg.counter(
            "shifu_role_flips_total",
            "Completed prefill/decode role flips (drain -> /rolez -> "
            "readiness gate -> resume) across the fleet",
        ).labels()
        self._g_envelope_util = reg.gauge(
            "shifu_envelope_utilization",
            "Worst-dimension serving-envelope utilization the "
            "controller last measured (1.0 = at the declared "
            "high-water mark)",
        ).labels()
        self._g_envelope_scale = reg.gauge(
            "shifu_envelope_admission_scale",
            "Batch-tier admission scale the controller last pushed "
            "fleet-wide (1.0 = admit freely, 0.0 = shed all backfill)",
        ).labels()
        self._g_envelope_scale.set(1.0)
        self._autoscale: Optional[dict] = None  # /statz autoscale block
        # shifu_slo_* per-tier traffic counters: the fleet SLO engine's
        # error-rate budget differences these over its burn windows
        # (obs/slo.py). Pre-seeded per tier so window deltas start at
        # an existing zero row instead of a missing series.
        self._c_slo_requests = reg.counter(
            "shifu_slo_requests_total",
            "Requests finished at this router by admission tier "
            "(completions + failures) — the fleet SLO engine's "
            "error-rate denominator", labelnames=("tier",),
        )
        self._c_slo_errors = reg.counter(
            "shifu_slo_errors_total",
            "Requests that FAILED at this router by admission tier "
            "(retry budget exhausted / non-retryable backend error) — "
            "the error-rate numerator", labelnames=("tier",),
        )
        for t in ("interactive", "batch"):
            self._c_slo_requests.labels(tier=t)
            self._c_slo_errors.labels(tier=t)
        # Fleet SLO engine + incident capture (obs/slo.py,
        # obs/incident.py) — attached via set_slo(); None until then
        # (slo_report answers None and /sloz serves an empty doc).
        self._slo = None
        self._incident = None
        self._g_budget.set(self.policy.budget)
        for b in self.backends:
            self._wire_backend(b)

    # ------------------------------------------------------- obs wiring
    def _wire_backend(self, b: BackendClient) -> None:
        lab = {"backend": b.addr}
        gauges = (
            self._g_breaker.labels(**lab), self._g_up.labels(**lab),
            self._g_inflight.labels(**lab),
        )
        gauges[0].set(CircuitBreaker.STATE_CODES[b.breaker.state])
        gauges[1].set(1.0 if b.routable() else 0.0)
        gauges[2].set(0.0)

        def on_transition(old: str, new: str, _b=b, _g=gauges):
            _g[0].set(CircuitBreaker.STATE_CODES[new])
            if new == CircuitBreaker.OPEN:
                _g[1].set(0.0)
                self.flight.record(
                    "backend_down", backend=_b.addr, was=old
                )
            elif new == CircuitBreaker.CLOSED and old != new:
                _g[1].set(1.0 if _b.routable() else 0.0)
                self.flight.record(
                    "backend_up", backend=_b.addr, was=old
                )

        b.breaker.on_transition = on_transition

    def probe_backend(self, b: BackendClient) -> dict:
        """One timed /healthz probe (the bootstrap prober's unit of
        work) — records the scrape-latency histogram alongside the
        breaker bookkeeping ``b.probe()`` already does, and feeds the
        NTP-style clock-offset estimator: the probe's send/receive wall
        stamps bracket the backend's ``wall_ms`` reading, giving one
        offset sample with error bound rtt/2 (min-RTT sample wins)."""
        t0 = time.monotonic()
        w0 = time.time() * 1000.0
        try:
            doc = b.probe()
            w1 = time.time() * 1000.0
            wall = doc.get("wall_ms") if isinstance(doc, dict) else None
            if wall is not None:
                try:
                    self._clock.note(b.addr, w0, w1, float(wall))
                except (TypeError, ValueError):
                    pass
            return doc
        finally:
            self._h_probe.labels(backend=b.addr).observe(
                time.monotonic() - t0
            )

    # ---------------------------------------------------------- routing
    @staticmethod
    def _role(b: BackendClient) -> str:
        return getattr(b, "role", "both") or "both"

    def _queue_score(self, b: BackendClient) -> float:
        """Remote queue depth with prefix-cache pressure folded in:
        occupancy (registered/total pages off the prober's /cachez
        scrape, 0..1) scaled by ``cache_weight`` — a FULL cache counts
        like ``cache_weight`` queued requests, so of two otherwise-
        equal hosts the one with cache headroom wins, while a genuine
        load gap still dominates. Backends never scraped score 0 extra
        (identical to the pre-sticky ordering)."""
        return b.queue_depth() + self.cache_weight * b.cache_occupancy()

    def _pick(self, exclude=(),
              model: Optional[str] = None) -> Optional[BackendClient]:
        """Least-loaded routable backend: fewest router-local in-flight
        requests, then shallowest remote queue + cache pressure
        (:meth:`_queue_score`), then lowest index (deterministic).
        ``model`` restricts to backends whose ``/v1/models`` listed
        that id (model-aware routing — the multi-tenant tier);
        unknown-model rejection happens at :meth:`submit`, so None here
        means "serving subset currently unavailable" (503), not 404.
        Consults ``breaker.allow()`` LAST and only on the
        winner-candidates, since allow() consumes the half-open probe
        slot.

        Roles are advisory, not partitions: colocated work AVOIDS
        prefill-role hosts (they sort last — their chip belongs to
        TTFT) but may still land there when nothing else is routable,
        so a decode-host outage degrades to slow instead of down."""
        order = sorted(
            (b for b in self.backends
             if b.routable() and b.addr not in exclude
             and (model is None or model in (b.model_ids or ()))),
            key=lambda b: (self._role(b) == "prefill", b.in_flight,
                           self._queue_score(b), self.backends.index(b)),
        )
        for b in order:
            if b.breaker.allow():
                return b
        return None

    def _pick_role(self, roles, exclude=(),
                   model: Optional[str] = None) -> Optional[BackendClient]:
        """``_pick`` restricted to backends whose probed role is in
        ``roles`` — the disaggregated path's phase-aware selection."""
        order = sorted(
            (b for b in self.backends
             if b.routable() and b.addr not in exclude
             and self._role(b) in roles
             and (model is None or model in (b.model_ids or ()))),
            key=lambda b: (b.in_flight, self._queue_score(b),
                           self.backends.index(b)),
        )
        for b in order:
            if b.breaker.allow():
                return b
        return None

    def submit(self, prompt_tokens, max_new_tokens: int, *,
               sampling: Optional[SampleConfig] = None,
               stop_token_ids=None, stop_strings=None,
               logit_bias=None, allowed_token_ids=None, adapter=None,
               regex=None, json_schema=None, model=None,
               tier: str = "interactive",
               trace: Optional[dict] = None,
               kv_export: bool = False, **kw) -> int:
        """Route one request (engine-thread call — no HTTP here).
        Raises :class:`FleetUnavailable` when no backend is routable,
        so a fully-down fleet fails fast instead of queueing forever.

        ``model``: model-aware routing. A named model routes
        least-loaded among the backends whose ``/v1/models`` listed it;
        an id NO roster backend (up, down, or draining) serves raises
        :class:`UnknownModelError` (-> 404 — the fleet is a multi-model
        tier and a typo'd id must not queue forever). None routes
        fleet-wide, and when no backend has reported its models yet the
        name is ignored rather than 404ing the whole fleet on a stale
        roster.

        ``trace``: distributed-trace context for this hop (dict with
        trace_id/span_id/[parent_id], usually the serving front-end's
        parsed ``x-shifu-trace`` header). None mints a fresh root — a
        routed request ALWAYS has a trace, so the fleet test can pull
        its merged timeline without opting in."""
        if kw:
            raise ValueError(f"unsupported submit fields: {sorted(kw)}")
        if kv_export:
            # The export verb belongs to a PREFILL HOST's engine (the
            # router is the one doing the fetching); accepting it here
            # would promise a /kv/pages payload this process cannot
            # serve.
            raise ValueError(
                "kv_export is a backend-engine field — the fleet "
                "router initiates handoffs itself, it does not export"
            )
        if model is not None:
            model = str(model)
            known = {
                m for b in self.backends
                for m in (b.model_ids or ())
            }
            if known and model not in known:
                raise UnknownModelError(
                    f"model {model!r} is not served by this fleet "
                    f"(served: {sorted(known)})"
                )
            if not known:
                model = None  # roster models unknown: route fleet-wide
        toks = [int(t) for t in prompt_tokens]
        if not toks:
            raise ValueError("empty prompt")
        body: dict = {
            "tokens": toks,
            "max_new_tokens": int(max_new_tokens),
            "stream": True,
            "logprobs": True,
        }
        if sampling is not None:
            for f in _SAMPLING_FIELDS:
                v = getattr(sampling, f)
                if v is not None:
                    body[f] = v
        if stop_token_ids:
            body["stop_token_ids"] = list(stop_token_ids)
        if stop_strings:
            body["stop"] = list(stop_strings)
        if logit_bias:
            body["logit_bias"] = {str(k): v for k, v in logit_bias.items()}
        if allowed_token_ids:
            body["allowed_token_ids"] = list(allowed_token_ids)
        if adapter is not None:
            body["adapter"] = int(adapter)
        if regex is not None:
            body["regex"] = regex
        if json_schema is not None:
            body["json_schema"] = json_schema
        tier = str(tier)
        if tier != "interactive":
            # The tier rides the wire so the BACKEND's engine admits it
            # through its own two-tier queue (interactive first, batch
            # backfills, preempt-not-drop) — the router adds no policy
            # of its own beyond SLO-window exemption.
            body["tier"] = tier

        if self._pick(model=model) is None:
            raise FleetUnavailable(
                "no routable fleet backend (all down/draining)"
                + (f" for model {model!r}" if model is not None else ""),
                retry_after_s=max(1.0, self.policy.cap_s),
            )
        if trace:
            ctx = _dtrace.TraceContext(
                str(trace.get("trace_id", "")) or _dtrace.mint().trace_id,
                str(trace.get("span_id", "")) or _dtrace.mint().span_id,
                str(trace.get("parent_id", "") or ""),
            )
        else:
            ctx = _dtrace.mint()
        with self._lock:
            rid = self._rid
            self._rid += 1
            req = _FleetRequest(rid, body, model=model, tier=tier,
                                trace=ctx)
            self._reqs[rid] = req
        threading.Thread(
            target=self._route_one, args=(req,),
            name=f"shifu-fleet-req-{rid}", daemon=True,
        ).start()
        return rid

    # ----------------------------------------------------- the worker
    def _attach(self, req: _FleetRequest, b: BackendClient) -> None:
        with self._lock:
            req.backend = b
            b.in_flight += 1
            b.routed += 1
        self._g_inflight.labels(backend=b.addr).set(b.in_flight)
        self._c_requests.labels(backend=b.addr).inc()

    def _detach(self, req: _FleetRequest, b: BackendClient) -> None:
        with self._lock:
            req.backend = None
            b.in_flight = max(0, b.in_flight - 1)
        self._g_inflight.labels(backend=b.addr).set(b.in_flight)

    def _route_one(self, req: _FleetRequest) -> None:
        try:
            self._route_one_inner(req)
        except Exception as e:  # worker bug must not strand the waiter
            self._finish(req, None, RuntimeError(
                f"fleet worker failed: {e!r}"
            ))

    def _route_one_inner(self, req: _FleetRequest) -> None:
        # Disaggregated fast path first: a prefill-heavy admission with
        # a prefill-role host available tries the two-host handoff.
        # _try_disagg returning True means the request is FINISHED
        # (completed disaggregated, or failed unretryably); False falls
        # through to the ordinary colocated loop below — a dead
        # prefill host or a losing breakeven degrades to exactly the
        # pre-disagg behavior.
        if self._disagg_eligible(req):
            if self._try_disagg(req):
                return
        attempt = 0
        # Sticky placement decides the FIRST attempt only (and may
        # migrate the session's KV pages before answering); retries
        # after a failure fall back to plain least-loaded _pick — the
        # sticky host just failed, re-pinning to it would be absurd.
        sticky = self._session_route(req) if self.sticky_sessions else None
        while True:
            if req.cancelled:
                self._finish(req, None, None)
                return
            att0 = time.monotonic()
            b, sticky = sticky, None
            if b is None:
                b = self._pick(model=req.model)
            if b is None:
                self._finish(req, None, FleetUnavailable(
                    "no routable fleet backend (all down/draining)"
                    + (f" for model {req.model!r}"
                       if req.model is not None else ""),
                    retry_after_s=max(1.0, self.policy.cap_s),
                ))
                return
            self._session_outcome(req, "new")
            if attempt == 0:
                # Content-addressed peer warm-up for the chosen host:
                # if a peer advertises this prompt's prefix and b does
                # not hold it, pull the chain before prefilling (best-
                # effort; a fault just means a cold prefill).
                self._peer_prefill(req, b)
            self._attach(req, b)
            try:
                err = self._run_stream(req, b,
                                       body=self._export_body(req, b))
            finally:
                self._detach(req, b)
            if err is None:
                return  # completed (or cancelled mid-stream)
            self._c_failures.labels(backend=b.addr).inc()
            if not err.retryable or req.streamed:
                # Validation rejection, or tokens already left the
                # router — the failure is the client's to see.
                self._finish(req, None, ValueError(str(err))
                             if not err.retryable else err)
                return
            if not self.policy.spend():
                self._g_budget.set(self.policy.budget)
                self._finish(req, None, FleetUnavailable(
                    f"retry budget exhausted after backend failure: {err}",
                    retry_after_s=max(1.0, self.policy.cap_s),
                ))
                return
            self._g_budget.set(self.policy.budget)
            b.retries += 1
            self._c_retries.labels(backend=b.addr).inc()
            with self._lock:
                self.resubmissions += 1
            if req.trace is not None:
                # The resubmit keeps its trace_id — the merged timeline
                # shows the failed attempt as a span, then the retried
                # hop, under ONE request.
                now = time.monotonic()
                self._span_store.add(req.trace.trace_id, _dtrace.span_record(
                    "resubmit", req.trace, att0 * 1000.0,
                    (now - att0) * 1000.0, rid=req.rid, backend=b.addr,
                    error=str(err), attempt=attempt,
                ))
            self._sleep(self.policy.delay(attempt))
            attempt += 1

    def _run_stream(self, req: _FleetRequest, b: BackendClient, *,
                    body: Optional[dict] = None,
                    prepend=None) -> Optional[BackendError]:
        """One attempt on one backend. Returns None on success (or
        deliberate cancel), else the failure. Breaker bookkeeping
        happens here — success closes, failure counts toward a trip.

        ``body`` overrides ``req.body`` on the wire (the disaggregated
        decode leg sends prompt+t1 with one fewer token of budget);
        ``prepend`` = ``(tokens, logprobs)`` already produced upstream
        (the prefill host's t1) — spliced into ``req.generated`` at the
        FIRST delta, not before, so a failure before any decode token
        leaves the request pristine for the colocated retry."""
        try:
            headers = (
                {_dtrace.HEADER: req.trace.child().to_header()}
                if req.trace is not None else None
            )
            stream = b.open_stream(
                body if body is not None else req.body, headers=headers
            )
        except BackendError as e:
            if e.retryable:
                b.breaker.record_failure()
            return e
        if req.cancelled:
            stream.close()
            b.breaker.record_success()
            self._finish(req, None, None)
            return None
        req.stream = stream
        final: Optional[dict] = None
        try:
            for ev in stream:
                if "error" in ev:
                    # The backend's post-200 failure surface. The
                    # ``retryable`` field is authoritative (the backend
                    # marks engine deaths retryable, validation errors
                    # not); absent (older backend) fall back to the
                    # engine-death message shape.
                    msg = str(ev["error"])
                    retryable = bool(ev.get(
                        "retryable",
                        "engine thread died" in msg
                        or "shut down" in msg,
                    ))
                    return BackendError(msg, retryable=retryable)
                if "finished_by" in ev:
                    final = ev
                    continue
                ids = ev.get("tokens")
                if ids:
                    if not req.streamed:
                        req.first_tok_at = time.monotonic()
                        if prepend:
                            req.generated.extend(prepend[0])
                            if prepend[1]:
                                req.logprobs.extend(prepend[1])
                    req.streamed = True
                    req.generated.extend(int(t) for t in ids)
                    lps = ev.get("logprobs")
                    if lps:
                        req.logprobs.extend(float(x) for x in lps)
                    self._progress.set()
        except BackendError as e:
            if req.cancelled:
                b.breaker.record_success()
                self._finish(req, None, None)
                return None
            b.breaker.record_failure()
            return e
        finally:
            req.stream = None
        if req.cancelled:
            b.breaker.record_success()
            self._finish(req, None, None)
            return None
        if final is None:
            b.breaker.record_failure()
            return BackendError(
                f"backend {b.addr} stream ended without a final event",
                retryable=True,
            )
        b.breaker.record_success()
        npre = len(prepend[0]) if prepend else 0
        self._complete_from(req, b, final, npre=npre)
        return None

    def _complete_from(self, req: _FleetRequest, b: BackendClient,
                       final: dict, npre: int = 0) -> None:
        """Close out a successfully streamed request: refund the retry
        budget, cut ``generated`` at the definitive token count, record
        timing + the router_hop span, and finish. ``npre`` = tokens
        spliced in from upstream (the disaggregated prefill host's t1)
        that the backend's own ``n_tokens`` does not count."""
        self.policy.refund()
        self._g_budget.set(self.policy.budget)
        n = int(final.get("n_tokens", len(req.generated) - npre)) + npre
        toks = list(req.generated[:n])
        lps = list(req.logprobs[:n]) if req.logprobs else None
        now = time.monotonic()
        total_ms = (now - req.submitted) * 1000.0
        ttft_ms = (
            (req.first_tok_at - req.submitted) * 1000.0
            if req.first_tok_at is not None else total_ms
        )
        decode_s = max(now - (req.first_tok_at or now), 1e-9)
        timing = {
            "backend": b.addr,
            "ttft_ms": round(ttft_ms, 3),
            "total_ms": round(total_ms, 3),
            "decode_tokens_per_s": round(max(n - 1, 0) / decode_s, 3)
            if n > 1 else None,
            "preemptions": 0,
        }
        if req.trace is not None:
            timing.update(req.trace.to_dict())
            timing["replica"] = self.replica_label
            self._span_store.add(
                req.trace.trace_id,
                _dtrace.span_record(
                    "router_hop", req.trace,
                    req.submitted * 1000.0, total_ms,
                    rid=req.rid, backend=b.addr, n_tokens=n,
                ),
            )
        b.note_latency(total_ms)
        self._affinity_note(req, b, final)
        self._h_request.labels(backend=b.addr).observe(total_ms / 1000.0)
        trace = {
            "ttft_ms": timing["ttft_ms"], "total_ms": timing["total_ms"],
            "preemptions": 0,
        }
        if timing["decode_tokens_per_s"]:
            trace["decode_tokens_per_s"] = timing["decode_tokens_per_s"]
        if req.tier == "batch":
            # Batch-tier completions stay out of the router's SLO
            # window (same contract as Engine.latency_stats): backfill
            # latency must not trip the watchdog's interactive p99
            # budgets or brake a rollout.
            with self._trace_lock:
                self.batch_completed += 1
        else:
            with self._trace_lock:
                self._trace_window.append(trace)
        self._finish(req, Completion(
            rid=req.rid, tokens=toks,
            finished_by=str(final.get("finished_by", "length")),
            logprobs=lps, timing=timing,
        ), None)

    # ------------------------------------- prefill/decode disaggregation
    def _disagg_eligible(self, req: _FleetRequest) -> bool:
        """Is this request worth a two-host handoff at all? Needs a
        prefill-heavy prompt (>= disagg_min_prompt tokens), a decode
        phase to migrate INTO (max_new >= 2), and a prefill-role host
        in the roster. Constrained decoding (regex/json_schema) and
        string stop sequences are excluded: their matcher state spans
        the prefill/decode boundary, and splitting would change where
        they fire relative to the colocated run — parity first."""
        body = req.body
        if body.get("regex") or body.get("json_schema") or body.get("stop"):
            return False
        if len(body.get("tokens") or ()) < self.disagg_min_prompt:
            return False
        if int(body.get("max_new_tokens", 0)) < 2:
            return False
        return any(
            self._role(b) == "prefill" and b.routable()
            for b in self.backends
        )

    def _disagg_wins(self, p_tokens: int,
                     dec: BackendClient) -> bool:
        """Measured migrate-vs-cold-prefill breakeven: predicted
        transfer time (prompt tokens x bytes/token EMA / bytes/ms EMA)
        against the decode host recomputing the prefill itself (its
        ``prefill_tok_per_ms`` from the last /healthz probe). Any side
        unmeasured -> True (explore — the EMAs need a sample before
        the comparison means anything; same policy as the host-tier
        restore-vs-recompute gate in infer/kvtier.py)."""
        bpm, bpt = self._xfer_bytes_per_ms, self._xfer_bytes_per_token
        rate = None
        if dec.health:
            try:
                r = dec.health.get("prefill_tok_per_ms")
                rate = float(r) if r else None
            except (TypeError, ValueError):
                rate = None
        if not bpm or not bpt or not rate:
            return True
        xfer_ms = (p_tokens * bpt) / bpm
        prefill_ms = p_tokens / rate
        return xfer_ms < prefill_ms

    def _note_xfer(self, nbytes: int, ms: float, tokens: int) -> None:
        """Fold one measured KV transfer (fetch + ingest wall time)
        into the breakeven EMAs (alpha 0.2, the kvtier.py pattern)."""
        if ms <= 0.0 or tokens <= 0 or nbytes <= 0:
            return
        a = 0.2
        bpm, bpt = nbytes / ms, nbytes / float(tokens)
        self._xfer_bytes_per_ms = (
            bpm if self._xfer_bytes_per_ms is None
            else (1 - a) * self._xfer_bytes_per_ms + a * bpm
        )
        self._xfer_bytes_per_token = (
            bpt if self._xfer_bytes_per_token is None
            else (1 - a) * self._xfer_bytes_per_token + a * bpt
        )

    def _disagg_host_note(self, addr: str, outcome: str) -> None:
        """Bump one prefill host's handoff-outcome count (caller holds
        ``self._lock``). Fleet rows carry these per host so the
        autoscale rebalancer can see WHICH hosts the disagg mix flows
        through, not just the fleet totals."""
        d = self._disagg_by_host.setdefault(
            addr, {"ok": 0, "failed": 0, "breakeven_loss": 0}
        )
        d[outcome] = d.get(outcome, 0) + 1

    def _try_disagg(self, req: _FleetRequest) -> bool:
        """One disaggregated attempt. True = the request is FINISHED
        (completed, or failed in a way the client must see); False =
        untouched (or cleanly rolled back) — the caller's colocated
        loop takes over. Handoff failure before the first decode token
        spends the ordinary retry budget and records a resubmit span,
        so a dead prefill host degrades to PR-5 colocated behavior
        with ``resubmissions`` counting the fallback."""
        pre = self._pick_role(("prefill",), model=req.model)
        if pre is None:
            return False
        dec = self._pick_role(("decode", "both"), exclude=(pre.addr,),
                              model=req.model)
        if dec is None:
            return False
        p_tokens = len(req.body.get("tokens") or ())
        if not self._disagg_wins(p_tokens, dec):
            with self._lock:
                self.disagg_breakeven_losses += 1
                self._disagg_host_note(pre.addr, "breakeven_loss")
            self._c_disagg.labels(outcome="breakeven_loss").inc()
            return False
        att0 = time.monotonic()
        err = self._run_disagg(req, pre, dec)
        if err is None:
            with self._lock:
                self.disagg_handoffs += 1
                self._disagg_host_note(pre.addr, "ok")
            self._c_disagg.labels(outcome="ok").inc()
            return True
        with self._lock:
            self.disagg_fallbacks += 1
            self._disagg_host_note(pre.addr, "failed")
        self._c_disagg.labels(outcome="failed").inc()
        self._c_failures.labels(backend=pre.addr).inc()
        if req.streamed or not err.retryable:
            # Decode tokens already left the router, or a validation
            # rejection — same terminal contract as the colocated path.
            self._finish(req, None, ValueError(str(err))
                         if not err.retryable else err)
            return True
        if not self.policy.spend():
            self._g_budget.set(self.policy.budget)
            self._finish(req, None, FleetUnavailable(
                f"retry budget exhausted after handoff failure: {err}",
                retry_after_s=max(1.0, self.policy.cap_s),
            ))
            return True
        self._g_budget.set(self.policy.budget)
        pre.retries += 1
        self._c_retries.labels(backend=pre.addr).inc()
        with self._lock:
            self.resubmissions += 1
        if req.trace is not None:
            now = time.monotonic()
            self._span_store.add(req.trace.trace_id, _dtrace.span_record(
                "resubmit", req.trace, att0 * 1000.0,
                (now - att0) * 1000.0, rid=req.rid, backend=pre.addr,
                error=str(err), attempt=0, phase="disagg",
            ))
        self._sleep(self.policy.delay(0))
        return False

    def _run_disagg(self, req: _FleetRequest, pre: BackendClient,
                    dec: BackendClient) -> Optional[BackendError]:
        """The handoff itself: (1) prefill leg — the full body with
        ``max_new_tokens: 1`` + ``kv_export: true`` on the prefill
        host, buffering t1 WITHOUT touching ``req.generated``; (2) the
        transfer — ``GET /kv/pages?rid=`` off the prefill host, relayed
        into the decode host's ``POST /kv/pages`` (one timed unit, the
        breakeven EMAs' sample); (3) decode leg — prompt+t1 with
        max_new-1 on the decode host, whose admission finds the
        ingested pages through the ordinary prefix-cache path (the PR 9
        parity contract, extended over the wire). The x-shifu-trace
        child rides every hop, so both hosts' kv_migrate spans land in
        one merged trace."""
        trace_hdr = (req.trace.child().to_header()
                     if req.trace is not None else None)
        headers = {_dtrace.HEADER: trace_hdr} if trace_hdr else None
        pbody = dict(req.body)
        pbody["max_new_tokens"] = 1
        pbody["kv_export"] = True
        toks: List[int] = []
        lps: List[float] = []
        pre_final: Optional[dict] = None
        payload = None
        x0 = None
        self._attach(req, pre)
        try:
            try:
                stream = pre.open_stream(pbody, headers=headers)
            except BackendError as e:
                if e.retryable:
                    pre.breaker.record_failure()
                return e
            try:
                for ev in stream:
                    if "error" in ev:
                        return BackendError(
                            str(ev["error"]),
                            retryable=bool(ev.get("retryable", False)),
                        )
                    if "finished_by" in ev:
                        pre_final = ev
                        continue
                    ids = ev.get("tokens")
                    if ids:
                        toks.extend(int(t) for t in ids)
                        l = ev.get("logprobs")
                        if l:
                            lps.extend(float(x) for x in l)
            except BackendError as e:
                pre.breaker.record_failure()
                return e
            if pre_final is None or not toks:
                pre.breaker.record_failure()
                return BackendError(
                    f"prefill backend {pre.addr} stream ended without "
                    "a final event", retryable=True,
                )
            pre.breaker.record_success()
            if str(pre_final.get("finished_by", "length")) != "length":
                # The request finished AT t1 (eos / stop id on the very
                # first token): there is no decode phase to migrate —
                # this IS the completion, bit-identical to colocated.
                req.first_tok_at = time.monotonic()
                req.streamed = True
                req.generated.extend(toks)
                req.logprobs.extend(lps)
                self._complete_from(req, pre, pre_final, npre=0)
                return None
            rid_remote = pre_final.get("rid")
            if rid_remote is None:
                return BackendError(
                    f"prefill backend {pre.addr} reported no rid — "
                    "cannot address its exported pages", retryable=True,
                )
            x0 = time.monotonic()
            try:
                payload = pre.kv_pages(int(rid_remote),
                                       trace_header=trace_hdr)
            except BackendError as e:
                pre.breaker.record_failure()
                return e
        finally:
            self._detach(req, pre)
        t1, lp1 = toks[0], (lps[0] if lps else None)
        self._attach(req, dec)
        try:
            try:
                dec.kv_ingest(payload, trace_header=trace_hdr)
            except BackendError as e:
                dec.breaker.record_failure()
                return e
            self._note_xfer(
                len(payload), (time.monotonic() - x0) * 1000.0,
                len(req.body.get("tokens") or ()),
            )
            dbody = dict(req.body)
            dbody["tokens"] = list(req.body["tokens"]) + [t1]
            dbody["max_new_tokens"] = int(req.body["max_new_tokens"]) - 1
            return self._run_stream(
                req, dec, body=dbody,
                prepend=([t1], [lp1] if lp1 is not None else []),
            )
        finally:
            self._detach(req, dec)

    # ------------------------------ sticky sessions + live migration
    @staticmethod
    def _affinity_salt(body: dict) -> bytes:
        """The chain-key salt — MUST match the engines' prefix-cache
        salt (PagedEngine._prefix_salt): empty for the base model,
        adapter-tagged otherwise, so a router-computed digest equals
        the digest the backend's cache files the same tokens under."""
        adapter = body.get("adapter")
        return b"" if adapter is None else f"adapter:{int(adapter)}".encode()

    def _session_outcome(self, req: _FleetRequest, outcome: str) -> None:
        """Record the request's placement outcome ONCE (first routing
        decision wins — retries after a failure don't reclassify)."""
        if not self.sticky_sessions or req.session_outcome is not None:
            return
        req.session_outcome = outcome
        with self._lock:
            self.session_counts[outcome] += 1
        self._c_session.labels(outcome=outcome).inc()

    def _export_body(self, req: _FleetRequest,
                     b: BackendClient) -> Optional[dict]:
        """The kv_export rider: sticky routing asks every host-tier
        backend to keep this request's prefill pages addressable
        (``kv_export: true`` -> the final event's ``rid`` -> a later
        ``GET /kv/pages`` can move the session). Returns the wire body
        override, or None to send ``req.body`` untouched (backend has
        no host tier, prompt too short to own a full chain page, or
        sticky routing is off). Clients still cannot set kv_export
        through :meth:`submit` — the router alone initiates this."""
        req.exported = False
        if not self.sticky_sessions or not b.has_host_tier():
            return None
        if len(req.body.get("tokens") or ()) < self.affinity_page:
            return None
        body = dict(req.body)
        body["kv_export"] = True
        req.exported = True
        return body

    def _affinity_lookup(self, req: _FleetRequest) -> Optional[dict]:
        """Match the prompt's prefix chain against the affinity table,
        DEEPEST key first (a follow-up turn's chain extends the turn
        that created the entry — the deepest hit is the most recent
        turn of the same session). Returns ``{"rec", "tokens"}`` (a
        copy of the entry + how many prompt tokens its chain covers)
        or None; stamps the computed keys + matched key on ``req`` so
        :meth:`_affinity_note` reuses them."""
        toks = req.body.get("tokens") or ()
        ps = self.affinity_page
        if len(toks) < ps:
            return None
        keys = chain_keys(toks, ps, self._affinity_salt(req.body))
        req.aff_keys = keys
        with self._affinity_lock:
            for i in range(len(keys) - 1, -1, -1):
                rec = self._affinity.get(keys[i])
                if rec is not None:
                    self._affinity.move_to_end(keys[i])
                    req.aff_key = keys[i]
                    return {"rec": dict(rec), "tokens": (i + 1) * ps}
        return None

    def _affinity_note(self, req: _FleetRequest, b: BackendClient,
                       final: dict) -> None:
        """Completion-side bookkeeping: remember that ``b`` now holds
        this prompt's KV under its deepest full-page chain key (and
        the export rid addressing it, when the wire body asked for
        one). The shallower key the lookup matched is DROPPED — the
        session slides forward through the table, one entry per live
        session, LRU-bounded at ``affinity_slots``."""
        if not self.sticky_sessions:
            return
        toks = req.body.get("tokens") or ()
        ps = self.affinity_page
        if len(toks) < ps:
            return
        keys = req.aff_keys
        if keys is None:
            keys = chain_keys(toks, ps, self._affinity_salt(req.body))
        rid = final.get("rid") if req.exported else None
        rec = {
            "addr": b.addr,
            "rid": int(rid) if rid is not None else None,
            "tokens": len(keys) * ps,
            "ts": time.time(),
        }
        with self._affinity_lock:
            if req.aff_key is not None and req.aff_key != keys[-1]:
                self._affinity.pop(req.aff_key, None)
            self._affinity[keys[-1]] = rec
            self._affinity.move_to_end(keys[-1])
            while len(self._affinity) > self.affinity_slots:
                self._affinity.popitem(last=False)
            n = len(self._affinity)
        self._g_affinity.set(float(n))

    def _sticky_hot(self, src: BackendClient) -> bool:
        """Should affinity yield to load? Only when the sticky host is
        ``sticky_hot_gap`` or more requests (in-flight + queued)
        BUSIER than the least-loaded routable alternative — mild
        imbalance stays sticky (the prefix cache pays for it), a
        genuinely hot host sheds its sessions."""
        load = src.in_flight + src.queue_depth()
        alts = [
            b.in_flight + b.queue_depth() for b in self.backends
            if b is not src and b.routable()
        ]
        return bool(alts) and load - min(alts) >= self.sticky_hot_gap

    def _session_route(self,
                       req: _FleetRequest) -> Optional[BackendClient]:
        """The sticky placement decision for a request's first
        attempt. Affinity hit on a healthy, not-hot host -> serve
        there (outcome ``sticky``). Sticky host unavailable (draining
        /drainz, mid-rollout, breaker-tripped, detached) or hot ->
        pick a new host; when the session's pages are addressable
        (export rid), BOTH hosts have tiers, the source isn't
        breaker-open (a dead socket must fail fast, not hang a
        fetch), and the measured breakeven favors the wire, MIGRATE
        the KV chain first (outcome ``migrated``), else cold-prefill
        (outcome ``rebalanced``). Returns the chosen backend, or None
        to let the caller's ordinary ``_pick`` run (outcome ``new``
        recorded there)."""
        hit = self._affinity_lookup(req)
        if hit is None:
            return None
        rec = hit["rec"]
        src = next(
            (b for b in self.backends if b.addr == rec["addr"]), None
        )
        routable_src = (
            src is not None and src.routable()
            and (req.model is None or req.model in (src.model_ids or ()))
        )
        if routable_src and not self._sticky_hot(src) \
                and src.breaker.allow():
            self._session_outcome(req, "sticky")
            return src
        dst = self._pick(exclude=(rec["addr"],), model=req.model)
        if dst is None:
            # Nowhere else to go: a hot (or half-open) sticky host
            # still beats a 503 when it can take the request at all.
            if routable_src and src.breaker.allow():
                self._session_outcome(req, "sticky")
                return src
            return None
        can_migrate = (
            src is not None
            and rec.get("rid") is not None
            and not src.detached
            and src.breaker.state != CircuitBreaker.OPEN
            and dst.has_host_tier()
        )
        if not can_migrate:
            self._session_outcome(req, "rebalanced")
            return dst
        if not self._disagg_wins(hit["tokens"], dst):
            # Same measured migrate-vs-cold-prefill gate as the
            # disaggregated path (shared EMAs — every SKVP transfer
            # teaches both): the wire would lose to dst recomputing.
            with self._lock:
                self.migrate_breakeven_losses += 1
            self._c_migrate.labels(outcome="breakeven_loss").inc()
            self._session_outcome(req, "rebalanced")
            return dst
        if self._migrate_session(req, src, dst, rec, hit["tokens"]):
            self._session_outcome(req, "migrated")
        else:
            self._session_outcome(req, "rebalanced")
        return dst

    def _migrate_session(self, req: _FleetRequest, src: BackendClient,
                         dst: BackendClient, rec: dict,
                         covered: int) -> bool:
        """Move the session's exported KV chain ``src`` -> ``dst``
        (``GET /kv/pages`` relayed into ``POST /kv/pages``, one timed
        unit feeding the breakeven EMAs) so the turn prefills WARM on
        the new host. False on any failure — the caller serves cold on
        ``dst`` instead; a migration must never cost more than the
        prefill it was avoiding, so there are no retries here. The
        trace child rides both legs (both hosts record kv_migrate
        spans) and the router adds its own kv_migrate span covering
        the full transfer."""
        trace_hdr = (req.trace.child().to_header()
                     if req.trace is not None else None)
        x0 = time.monotonic()
        leg = src
        try:
            payload = src.kv_pages(int(rec["rid"]),
                                   trace_header=trace_hdr)
            leg = dst
            dst.kv_ingest(payload, trace_header=trace_hdr)
        except BackendError as e:
            # Attribute the failure to the host whose leg broke — a
            # dead source trips ITS breaker (later turns skip straight
            # to cold prefill), not the healthy destination's.
            leg.breaker.record_failure()
            with self._lock:
                self.migrate_fallbacks += 1
            self._c_migrate.labels(outcome="failed").inc()
            self.flight.record(
                "session_migrate_failed", rid=req.rid, src=src.addr,
                dst=dst.addr, at=leg.addr, error=str(e),
            )
            return False
        ms = (time.monotonic() - x0) * 1000.0
        self._note_xfer(len(payload), ms, covered)
        with self._lock:
            self.migrations += 1
            self.migrate_bytes += len(payload)
        self._c_migrate.labels(outcome="ok").inc()
        self._c_migrate_bytes.inc(float(len(payload)))
        self._h_migrate.observe(ms / 1000.0)
        if req.trace is not None:
            self._span_store.add(req.trace.trace_id, _dtrace.span_record(
                "kv_migrate", req.trace, x0 * 1000.0, ms, rid=req.rid,
                src=src.addr, dst=dst.addr, nbytes=len(payload),
                tokens=covered,
            ))
        self.flight.record(
            "session_migrated", rid=req.rid, src=src.addr, dst=dst.addr,
            nbytes=len(payload), ms=round(ms, 3), tokens=covered,
        )
        return True

    # ------------------------ content-addressed peer fetch (tier 3)
    def fleet_digest_map(self) -> Dict[str, List[BackendClient]]:
        """Digest hex -> backends holding it, folded from each
        backend's cached /cachez ``digests`` advertisement. Rebuilt
        only when some backend's scrape timestamp moved (the prober
        refreshes /cachez every tick) — reading the map never blocks
        on the wire."""
        sig = tuple((b.addr, b.cache_ts) for b in self.backends)
        with self._peer_lock:
            if sig == self._digest_map_sig:
                return self._digest_map
        m: Dict[str, List[BackendClient]] = {}
        for b in self.backends:
            if b.detached:
                continue
            for d in b.held_digests():
                m.setdefault(d, []).append(b)
        with self._peer_lock:
            self._digest_map = m
            self._digest_map_sig = sig
        return m

    def _peer_page_sizes(self) -> List[int]:
        """Distinct page sizes advertised across the fleet — chain
        digests are page-size-dependent, so the prompt's keys must be
        computed per advertised geometry (typically one value)."""
        sizes: List[int] = []
        for b in self.backends:
            dg = (b.cache or {}).get("digests") or {}
            try:
                ps = int(dg.get("page_size") or 0)
            except (TypeError, ValueError):
                ps = 0
            if ps > 0 and ps not in sizes:
                sizes.append(ps)
        return sizes

    def _peer_wins(self, src: BackendClient, tokens: int,
                   dst: BackendClient) -> bool:
        """Measured fetch-vs-recompute breakeven, per SOURCE: this
        source's fetch bytes/ms EMA against the destination
        recomputing the prefill itself (its ``prefill_tok_per_ms``
        from the last probe); the bytes estimate rides the shared
        bytes/token EMA. Any side unmeasured -> True (explore — same
        policy as every other breakeven gate in this file)."""
        bpm = self._peer_bw.get(src.addr)
        bpt = self._xfer_bytes_per_token
        rate = None
        if dst.health:
            try:
                r = dst.health.get("prefill_tok_per_ms")
                rate = float(r) if r else None
            except (TypeError, ValueError):
                rate = None
        if not bpm or not bpt or not rate:
            return True
        return (tokens * bpt) / bpm < tokens / rate

    def _peer_prefill(self, req: _FleetRequest,
                      dst: BackendClient) -> None:
        """Before a cold attempt on ``dst``: if some OTHER backend
        advertises a prefix of this prompt (deepest chain digest wins)
        and dst does not already hold it, fetch the chain digest-keyed
        from the holder and ingest it into dst so the prompt prefills
        warm. Strictly best-effort — any fault leaves the request
        exactly as cold as it already was."""
        try:
            if not dst.has_host_tier():
                return
            m = self.fleet_digest_map()
            toks = req.body.get("tokens") or ()
            if not m or not toks:
                return
            mine = dst.held_digests()
            salt = self._affinity_salt(req.body)
            for ps in self._peer_page_sizes():
                if len(toks) < ps:
                    continue
                keys = chain_keys(toks, ps, salt)
                for i in range(len(keys) - 1, -1, -1):
                    d = keys[i].hex()
                    if d in mine:
                        return  # dst's deepest prefix >= the fleet's
                    holders = [
                        h for h in m.get(d, ())
                        if h is not dst and h.routable()
                    ]
                    if holders:
                        self._peer_fetch(
                            req, holders[0], dst, d, (i + 1) * ps
                        )
                        return
        except Exception:  # noqa: BLE001 — never block the request
            pass

    def _peer_fetch(self, req: Optional[_FleetRequest],
                    src: BackendClient, dst: BackendClient,
                    digest: str, covered: int, *,
                    gate: bool = True) -> bool:
        """One digest-keyed fetch+ingest, src -> dst (one timed unit
        that teaches the per-source bandwidth EMA and the shared
        transfer EMAs). False on a breakeven loss or either leg
        failing — the caller proceeds cold either way."""
        if gate and not self._peer_wins(src, covered, dst):
            with self._lock:
                self.peer_breakeven_losses += 1
            self._c_peer.labels(outcome="breakeven_loss").inc()
            return False
        trace_hdr = (
            req.trace.child().to_header()
            if req is not None and req.trace is not None else None
        )
        x0 = time.monotonic()
        leg = src
        try:
            payload = src.kv_pages_digest(digest,
                                          trace_header=trace_hdr)
            leg = dst
            out = dst.kv_ingest(payload, trace_header=trace_hdr)
        except BackendError as e:
            # Attribute the failure to the host whose leg broke, like
            # session migration does.
            leg.breaker.record_failure()
            with self._lock:
                self.peer_failures += 1
            self._c_peer.labels(outcome="failed").inc()
            self.flight.record(
                "kv_peer_fetch_failed", src=src.addr, dst=dst.addr,
                digest=digest, at=leg.addr, error=str(e),
            )
            return False
        ms = (time.monotonic() - x0) * 1000.0
        pages = int(out.get("pages", 0) or 0)
        a = 0.2
        bpm = len(payload) / max(ms, 1e-9)
        cur = self._peer_bw.get(src.addr)
        self._peer_bw[src.addr] = (
            bpm if cur is None else (1 - a) * cur + a * bpm
        )
        self._note_xfer(len(payload), ms, covered)
        with self._lock:
            self.peer_fetches += 1
            self.peer_pages += pages
            self.peer_bytes += len(payload)
        self._c_peer.labels(outcome="ok").inc()
        self._c_peer_pages.inc(float(pages))
        self._c_peer_bytes.inc(float(len(payload)))
        self.flight.record(
            "kv_peer_fetch", src=src.addr, dst=dst.addr,
            digest=digest, pages=pages, nbytes=len(payload),
            ms=round(ms, 3), tokens=covered,
        )
        return True

    def maybe_peer_warm(self, limit: int = 8) -> int:
        """Warm every stone-cold host-tier backend from its peers: a
        scraped backend advertising NO digests (fresh bootstrap or
        autoscale join) gets the fleet's chain TIPS (held digests that
        are no other held digest's parent — each tip's export carries
        its whole chain) pushed into its tiers, once per backend. No
        breakeven gate — warming is explicitly exploratory and runs
        off the request path (prober tick / build_fleet). A backend is
        marked warmed when a chain lands or there was nothing to fetch;
        a warmup whose every fetch FAILED (e.g. a timeout during the
        startup scramble) stays eligible, so the next prober tick
        retries instead of leaving the host cold forever — bounded at
        three all-failed rounds, so a deterministic refusal (a
        page-size-mismatched fleet) cannot flap the destination's
        breaker every tick from here. Returns the number of chains
        moved."""
        m = self.fleet_digest_map()
        if not m:
            return 0
        moved = 0
        for dst in self.backends:
            if (dst.addr in self._peer_warmed or dst.detached
                    or not dst.routable() or not dst.has_host_tier()
                    or dst.held_digests()):
                continue
            parents = set()
            for b in self.backends:
                for par in b.held_digests().values():
                    if par:
                        parents.add(par)
            tips = [d for d in m if d not in parents]
            got = 0
            attempted = 0
            for d in tips:
                if got >= int(limit):
                    break
                holders = [
                    h for h in m.get(d, ())
                    if h is not dst and h.routable()
                ]
                if not holders:
                    continue
                attempted += 1
                if self._peer_fetch(
                    None, holders[0], dst, d, 0, gate=False
                ):
                    got += 1
            if got or not attempted:
                self._peer_warmed.add(dst.addr)
                self._peer_warm_strikes.pop(dst.addr, None)
            else:
                strikes = self._peer_warm_strikes.get(dst.addr, 0) + 1
                self._peer_warm_strikes[dst.addr] = strikes
                if strikes >= 3:
                    self._peer_warmed.add(dst.addr)
                    self.flight.record(
                        "kv_peer_warmup_abandoned", backend=dst.addr,
                        strikes=strikes,
                    )
            if got:
                moved += got
                with self._lock:
                    self.peer_warmups += got
                dst.refresh_cachez()
                self.flight.record(
                    "kv_peer_warmup", backend=dst.addr, chains=got,
                )
        return moved

    def peer_stats(self) -> dict:
        """The /cachez ``peer`` block (and ``obs top``'s peer line):
        content-addressed fetch totals plus which backends were
        bulk-warmed on join."""
        with self._lock:
            return {
                "fetches": self.peer_fetches,
                "failures": self.peer_failures,
                "breakeven_losses": self.peer_breakeven_losses,
                "pages": self.peer_pages,
                "bytes": self.peer_bytes,
                "warmups": self.peer_warmups,
                "warmed_backends": sorted(self._peer_warmed),
            }

    def session_stats(self) -> Optional[dict]:
        """The /statz ``session`` block (and ``obs top``'s session
        line): affinity-table occupancy, per-outcome request counts,
        the warm-placement rate (sticky + migrated over everything
        sticky routing classified), and migration totals. None when
        sticky routing is disabled."""
        if not self.sticky_sessions:
            return None
        with self._lock:
            counts = dict(self.session_counts)
            m_ok = self.migrations
            m_fail = self.migrate_fallbacks
            m_loss = self.migrate_breakeven_losses
            m_bytes = self.migrate_bytes
        with self._affinity_lock:
            entries = len(self._affinity)
        total = sum(counts.values())
        warm = counts["sticky"] + counts["migrated"]
        return {
            "affinity_entries": entries,
            "affinity_slots": self.affinity_slots,
            "affinity_page": self.affinity_page,
            "requests": counts,
            "sticky_hit_rate": round(warm / total, 4) if total else None,
            "migrations": m_ok,
            "migrate_fallbacks": m_fail,
            "migrate_breakeven_losses": m_loss,
            "migrate_bytes": m_bytes,
        }

    def _finish(self, req: _FleetRequest, completion, error) -> None:
        with self._lock:
            if self._reqs.pop(req.rid, None) is None:
                return  # cancelled and reaped already
            if completion is not None:
                self.requests_completed += 1
                self.tokens_generated += len(completion.tokens)
                self._done.append(completion)
            elif error is not None:
                self._done.append(("error", req.rid, error))
        self._c_slo_requests.labels(tier=req.tier).inc()
        if error is not None:
            self._c_slo_errors.labels(tier=req.tier).inc()
        self._progress.set()

    # ------------------------------------------------------ driving
    def cancel(self, rid: int) -> bool:
        """Cancel wherever the request is: not-yet-attached workers see
        the flag before opening a stream; attached ones have their
        backend connection CLOSED, which frees the remote slot (the
        backend server's documented disconnect-cancel path)."""
        with self._lock:
            req = self._reqs.pop(rid, None)
            if req is None:
                return False
            req.cancelled = True
            self.cancellations += 1
            stream = req.stream
        if stream is not None:
            stream.close()
        return True

    def step(self) -> List[Completion]:
        """Wait briefly for worker progress, then return completions.
        Per-request FAILURES do not raise here (that would trip the
        runner's fatal path and kill the whole router for one lost
        backend); they queue for :meth:`failures`, the per-request
        failure surface the runner drains after each step to fail
        exactly the affected waiter (503/400 for that caller only)."""
        if not self._done:
            self._progress.wait(self._step_wait_s)
            self._progress.clear()
        done: List[Completion] = []
        with self._lock:
            while self._done:
                item = self._done.popleft()
                if isinstance(item, Completion):
                    done.append(item)
                else:
                    self._failures[item[1]] = item[2]
        return done

    def failures(self) -> Dict[int, Exception]:
        """Per-request failures since the last call (rid -> exception).
        Part of ``ENGINE_INTERFACE``: in-process engines return ``{}``
        (they complete or die whole), the fleet fails requests
        INDIVIDUALLY when a backend dies with their tokens streamed or
        the retry budget runs out."""
        with self._lock:
            out, self._failures = self._failures, {}
        return out

    def step_dispatch(self):
        return None

    def step_fold(self, _handle) -> List[Completion]:
        return self.step()

    def run(self) -> List[Completion]:
        out: List[Completion] = []
        while not self.idle:
            out.extend(self.step())
        return out

    @property
    def idle(self) -> bool:
        return not self._reqs and not self._done and not self._failures

    # -------------------------------------------- streaming surface
    def live_requests(self) -> List[LiveRequest]:
        with self._lock:
            return [
                LiveRequest(
                    rid=r.rid, generated=r.generated, logprobs=r.logprobs
                )
                for r in self._reqs.values()
            ]

    def live_generated(self) -> Dict[int, List[int]]:
        with self._lock:
            return {r.rid: r.generated for r in self._reqs.values()}

    @property
    def active_slots(self) -> int:
        with self._lock:
            return len(self._reqs)

    @property
    def max_slots(self) -> int:
        tot = 0
        for b in self.backends:
            h = b.health or {}
            try:
                tot += int(h.get("max_slots", 0))
            except (TypeError, ValueError):
                pass
        return tot

    # ------------------------------------------------------- adapters
    def add_adapter(self, lora_params) -> int:
        raise ValueError(
            "register LoRA adapters on the backend hosts; the fleet "
            "router holds no params"
        )

    def reload_params(self, params) -> None:
        raise ValueError(
            "the fleet router holds no params; hot-swap weights on the "
            "backend hosts (POST /reloadz per host, or drive the whole "
            "fleet with `shifu_tpu fleet rollout`)"
        )

    # ------------------------------------------------- model routing
    def served_models(self) -> dict:
        """The multi-tenant roster: {model_id: {"backends": [...],
        "max_len": min-across-them, "ckpts": [...]}} aggregated from
        each attached backend's last ``/v1/models``. The serving
        front-end renders this as the router's own ``/v1/models`` and
        404s requests naming an id absent here. Mixed ``ckpts`` mid-
        rollout is the expected transient — the /statz reader SEES the
        fleet straddling two versions."""
        out: dict = {}
        for b in self.backends:
            if b.detached or not b.model_ids:
                continue
            for mid in b.model_ids:
                ent = out.setdefault(
                    mid, {"backends": [], "max_len": None, "ckpts": []}
                )
                ent["backends"].append(b.addr)
                if b.max_len is not None:
                    ent["max_len"] = (
                        b.max_len if ent["max_len"] is None
                        else min(ent["max_len"], b.max_len)
                    )
                if b.ckpt and b.ckpt not in ent["ckpts"]:
                    ent["ckpts"].append(b.ckpt)
        for ent in out.values():
            ent["backends"].sort()
            ent["ckpts"].sort()
        return out

    @property
    def n_adapters(self) -> int:
        vals = []
        for b in self.backends:
            h = b.health or {}
            if isinstance(h.get("n_adapters"), int):
                vals.append(h["n_adapters"])
        return min(vals) if vals else 0

    def cache_stats(self):
        """``GET /cachez`` pass-through: one prefix-cache/host-tier
        block per attached backend (live scrape, probe timeout each) —
        the per-backend occupancy + hit-rate surface prefix-aware
        sticky routing scores with (ROADMAP item 2). A backend that
        cannot answer reports its error in place of a block; detached
        (draining) backends are skipped — their caches are about to be
        irrelevant to placement."""
        out = {}
        for b in self.backends:
            if b.detached:
                continue
            try:
                out[b.addr] = b.cachez()
            except Exception as e:  # noqa: BLE001 — per-backend fault
                out[b.addr] = {"error": str(e)}
        doc = {"backends": out}
        # Duck-typed callers (tests drive this unbound on fakes) may
        # not carry the peer-fetch surface.
        if isinstance(getattr(self, "_peer_warmed", None), set):
            doc["peer"] = self.peer_stats()
        return doc

    def queue_depths(self) -> Dict[str, int]:
        """Per-tier backlog at THIS router: accepted requests whose
        first token has not streamed yet, plus the backends' last-
        probed batch queue depths (each backend's /healthz carries its
        engine's ``queued_batch``). The server's batch admission cap
        (429 + Retry-After) reads the "batch" entry — it bounds what a
        runaway job can pile onto the fleet through this router."""
        out = {"interactive": 0, "batch": 0}
        with self._lock:
            for r in self._reqs.values():
                if not r.streamed:
                    out[r.tier] = out.get(r.tier, 0) + 1
        for b in self.backends:
            h = b.health or {}
            try:
                out["batch"] += int(h.get("queued_batch", 0))
            except (TypeError, ValueError):
                pass
        return out

    # ---------------------------------------------------- aggregation
    def counters(self) -> dict:
        """Pooled counters: the router's own lifecycle counts plus the
        sum of each backend's last-probed numeric counters, and the
        per-backend breakdown (the fleet's load-balance surface)."""
        out = {
            "active_slots": self.active_slots,
            "max_slots": self.max_slots,
            "queued": sum(
                1 for r in list(self._reqs.values()) if not r.streamed
            ) + sum(b.queue_depth() for b in self.backends),
            "cancellations": self.cancellations,
            "requests_completed": self.requests_completed,
            "tokens_generated": self.tokens_generated,
            "batch_completed": self.batch_completed,
            "resubmissions": self.resubmissions,
            "retry_budget": round(self.policy.budget, 2),
            "disagg_handoffs": self.disagg_handoffs,
            "disagg_fallbacks": self.disagg_fallbacks,
            "disagg_breakeven_losses": self.disagg_breakeven_losses,
            "peer_fetches": self.peer_fetches,
            "peer_failures": self.peer_failures,
            "peer_breakeven_losses": self.peer_breakeven_losses,
            "peer_pages": self.peer_pages,
            "peer_bytes": self.peer_bytes,
            "peer_warmups": self.peer_warmups,
        }
        if self.sticky_sessions:
            with self._lock:
                out.update(
                    session_sticky=self.session_counts["sticky"],
                    session_new=self.session_counts["new"],
                    session_migrated=self.session_counts["migrated"],
                    session_rebalanced=self.session_counts["rebalanced"],
                    migrations=self.migrations,
                    migrate_fallbacks=self.migrate_fallbacks,
                    migrate_breakeven_losses=self.migrate_breakeven_losses,
                )
            with self._affinity_lock:
                out["affinity_entries"] = len(self._affinity)
        if self._xfer_bytes_per_ms is not None:
            # The breakeven's learned wire speed — operators read this
            # next to each decode host's prefill_tok_per_ms to see WHY
            # the router is (not) disaggregating.
            out["kv_xfer_bytes_per_ms"] = round(
                self._xfer_bytes_per_ms, 3
            )
            out["kv_xfer_bytes_per_token"] = round(
                self._xfer_bytes_per_token, 3
            )
        per = []
        for b in self.backends:
            ent = {
                "backend": b.addr, "status": b.status(),
                "breaker": b.breaker.state, "routed": b.routed,
                "retries": b.retries, "in_flight": b.in_flight,
                "queued_remote": b.queue_depth(),
                "role": self._role(b),
            }
            if b.ewma_ms is not None:
                ent["ewma_ms"] = round(b.ewma_ms, 3)
            per.append(ent)
        out["backends"] = per
        return out

    def latency_stats(self) -> dict:
        """Router-measured pooled latency window (same keys as
        ``Engine.latency_stats`` so the SLO watchdog's TTFT/ITL budgets
        read it unchanged). TTFT here includes the hop to the backend —
        the fleet's honest client-visible number."""
        with self._trace_lock:
            win = list(self._trace_window)
            batch = self.batch_completed
        extra = {"batch_completions": batch} if batch else {}
        if not win:
            return {"completions": 0, **extra}

        def pct(key, q):
            vals = sorted(t[key] for t in win if key in t)
            if not vals:
                return None
            return vals[min(int(q * len(vals)), len(vals) - 1)]

        out = {
            **extra,
            "completions": len(win),
            "ttft_ms_p50": pct("ttft_ms", 0.50),
            "ttft_ms_p95": pct("ttft_ms", 0.95),
            "ttft_ms_p99": pct("ttft_ms", 0.99),
            "decode_tokens_per_s_p50": pct("decode_tokens_per_s", 0.50),
            "decode_tokens_per_s_p05": pct("decode_tokens_per_s", 0.05),
            "preempted_fraction": 0.0,
        }
        slow = pct("decode_tokens_per_s", 0.01)
        if slow:
            out["req_itl_ms_p99"] = round(1000.0 / slow, 3)
        return out

    # ----------------------------------------------- distributed traces
    def trace_spans(self, trace_id) -> List[dict]:
        """The fleet's /tracez collector: the router's own span-store
        slice plus every attached backend's, each backend doc stamped
        with the prober's clock offset (= backend_wall - router_wall)
        so ``merge_host_docs`` lands all spans on THIS process's wall
        clock. A backend that cannot answer is skipped — a partial
        trace beats none while a host is down."""
        docs = [_dtrace.host_doc(
            self.host_label, self._span_store.get(trace_id),
            replica=self.replica_label,
        )]
        for b in self.backends:
            if b.detached:
                continue
            try:
                remote = b.tracez(trace_id)
            except Exception:  # noqa: BLE001 — per-backend fault
                continue
            off, err = self._clock.offset(b.addr)
            if not math.isfinite(err):
                off, err = 0.0, 0.0  # never probed: assume shared clock
            for h in remote.get("hosts", ()):
                if not isinstance(h, dict):
                    continue
                h = dict(h)
                h["offset_ms"] = float(h.get("offset_ms", 0.0)) + off
                h["err_ms"] = float(h.get("err_ms", 0.0)) + err
                docs.append(h)
        return docs

    # ------------------------------------------------------- federation
    def federated_metrics(self) -> str:
        """Scrape every attached backend's /metrics, re-emit each
        ``shifu_*`` sample under ``shifu_fleet_agg_*`` — pooled (summed
        across backends; histogram buckets are cumulative so the
        per-``le`` sum is exact) and per-backend (``backend`` label).
        The server appends this text to the router's own /metrics, so
        one scrape of the router shows the whole fleet. Unreachable
        backends are skipped (federation must not take /metrics down
        with a host)."""
        from shifu_tpu.obs.registry import parse_exposition

        parsed: Dict[str, Dict[tuple, float]] = {}
        for b in self.backends:
            if b.detached:
                continue
            try:
                parsed[b.addr] = parse_exposition(b.metrics_text())
            except Exception:  # noqa: BLE001 — per-backend fault
                continue
        text, pooled = _dtrace.federate(parsed)
        with self._fed_lock:
            self._fed_pooled = pooled
        return text

    def federated_quantile(self, family: str, q: float,
                           labels=None) -> Optional[float]:
        """Estimated quantile over the POOLED federated histogram from
        the last ``federated_metrics`` scrape (the SLO watchdog's
        fleet-wide budget view). None before any scrape or when the
        family has no pooled buckets."""
        with self._fed_lock:
            pooled = self._fed_pooled
        if not pooled:
            return None
        return _dtrace.quantile_from_pooled(pooled, family, q, labels)

    # --------------------------------------------------- fleet SLO engine
    def set_slo(self, slo, incident=None) -> None:
        """Attach the fleet SLO engine (obs/slo.py) and, optionally,
        the incident-bundle writer (obs/incident.py). The engine's
        breach transitions route through :meth:`_on_slo_breach` so a
        burning tier captures cross-host forensics automatically."""
        self._slo = slo
        self._incident = incident
        if slo is not None:
            slo.on_breach = self._on_slo_breach

    def recent_trace_ids(self, n: int = 3) -> List[str]:
        """The router span store's newest trace ids — the incident
        capture's merged-trace selection."""
        return self._span_store.recent(n)

    def _slo_sample(self) -> Dict[tuple, float]:
        """One pooled sample for the SLO engine: a fresh federation
        scrape (the backends' tier-labelled latency histograms, pooled
        per ``le`` edge) merged with this router's OWN registry parse
        (the per-tier request/error counters live here)."""
        from shifu_tpu.obs.registry import parse_exposition

        self.federated_metrics()
        with self._fed_lock:
            merged = dict(self._fed_pooled)
        merged.update(parse_exposition(self.metrics.render()))
        return merged

    def slo_report(self) -> Optional[dict]:
        """ENGINE_INTERFACE ``slo_report`` — the ``GET /sloz`` payload.
        None when no SLO engine is attached (in-process engines, fleet
        routers without declared budgets). Sampling is pull-driven with
        a minimum interval: /sloz scrapes and the SLOMonitor thread
        both land here, and the engine decides when a new federation
        scrape is due."""
        slo = self._slo
        if slo is None:
            return None
        if slo.sample_due():
            slo.note(self._slo_sample())
        return slo.evaluate()

    def _on_slo_breach(self, tier: str, info: dict) -> None:
        """A tier left ``ok``: capture a cross-host incident bundle in
        the background (the capture makes fleet-wide HTTP fetches — it
        must not stall the evaluation path that detected the breach).
        Rate limiting lives in the writer, checked atomically, so a
        flapping budget produces one bundle per quiet period."""
        inc = self._incident
        if inc is None:
            return
        reason = (
            f"tier {tier} {info.get('status')}: burn_rate "
            f"{info.get('burn_rate')}, headroom {info.get('headroom')}"
        )
        slo_doc = {"tiers": {tier: info}}

        def _capture():
            try:
                inc.capture(self, tier=tier, reason=reason, slo=slo_doc)
            except Exception:  # noqa: BLE001 — forensics best-effort
                pass

        threading.Thread(
            target=_capture, name=f"shifu-incident-{tier}", daemon=True,
        ).start()

    # ENGINE_INTERFACE KV-handoff surface: the router fronts no page
    # pool — its /kv/pages routes answer 404 (no payload) and 400 (no
    # pool); the real surfaces live on the prefill/decode hosts.
    def kv_export_payload(self, rid, trace=None):
        return None

    def kv_export_digest(self, digest, trace=None):
        return None

    def kv_ingest(self, payload, trace=None):
        raise ValueError(
            "the fleet router holds no page pool; POST /kv/pages to a "
            "decode-role backend directly"
        )

    # ----------------------------------------------------- fleet admin
    def health_reasons(self) -> List[str]:
        """Non-SLO health findings for /healthz: every tripped backend
        is NAMED (a degraded fleet must say which host is gone)."""
        out = []
        for b in self.backends:
            if b.detached:
                continue
            if b.breaker.state == CircuitBreaker.OPEN:
                out.append(f"backend {b.addr} down (circuit breaker open)")
        if not any(
            b.routable() and b.breaker.state != CircuitBreaker.OPEN
            for b in self.backends
        ):
            out.append("no routable backend remains")
        return out

    def fleet_stats(self) -> dict:
        """The /statz fleet block: one row per backend (healthz status
        + the backend watchdog's reason strings, remote queue depth,
        breaker state, EWMA latency) + the shared retry budget. The
        watchdog fields mirror each host's own /healthz so a degraded
        backend is visible from the ROUTER's one pane of glass."""
        rows = []
        for b in self.backends:
            h = b.health or {}
            row = {
                "backend": b.addr,
                "status": b.status(),
                "breaker": b.breaker.state,
                "healthz": h.get("status"),
                "healthz_reasons": list(
                    h.get("degraded_reasons") or ()
                ),
                "queue_depth": b.queue_depth(),
                "in_flight": b.in_flight,
                "routed": b.routed,
                "retries": b.retries,
                "ewma_ms": round(b.ewma_ms, 3)
                if b.ewma_ms is not None else None,
                "last_probe_ts": b.health_ts,
                "max_len": b.max_len,
                "role": self._role(b),
                # The autoscale rebalancer's per-host inputs, mirrored
                # off the prober's last /healthz scrape: measured
                # prefill rate, HBM high-water fraction (absent on
                # hosts whose devices report no limits — the envelope
                # scrape gap), and this host's disagg handoff
                # outcomes as the chosen PREFILL side.
                "prefill_tok_per_ms": h.get("prefill_tok_per_ms"),
                "hbm_frac_used": h.get("hbm_frac_used"),
                "disagg": dict(
                    self._disagg_by_host.get(b.addr) or {}
                ),
            }
            if b.cache is not None:
                # The prober's last /cachez scrape — the numbers the
                # sticky score routes on, shown per host so an
                # operator sees WHY placement prefers a backend.
                row["cache_occupancy"] = round(b.cache_occupancy(), 4)
                row["cache_hit_rate"] = b.cache_hit_rate()
                row["host_tier"] = b.has_host_tier()
            rows.append(row)
        return {
            "backends": rows,
            "retry_budget": round(self.policy.budget, 2),
            "resubmissions": self.resubmissions,
        }

    def _backend(self, target: str) -> BackendClient:
        b = next(
            (x for x in self.backends if x.addr == str(target)), None
        )
        if b is None:
            raise ValueError(
                f"unknown backend {target!r} (roster: "
                f"{[x.addr for x in self.backends]})"
            )
        return b

    def drain(self, target: str, detach: bool = True) -> dict:
        """``POST /drainz``: stop routing NEW work to ``target``
        (``host:port``) and let its in-flight streams finish. With
        ``detach=True`` (the operator-removal default) a daemon thread
        then detaches it permanently; ``detach=False`` is the ROLLING-
        UPDATE form — the backend stays in the roster, drained, until
        :meth:`resume` re-admits it (the rollout controller's
        drain -> reload -> readiness-gate -> resume walk). Returns
        immediately with the in-flight count."""
        b = self._backend(target)
        if b.detached:
            raise ValueError(f"backend {target!r} is already detached")
        already = b.draining
        b.draining = True
        self._g_up.labels(backend=b.addr).set(0.0)
        if not already:
            self.flight.record(
                "backend_draining", backend=b.addr,
                in_flight=b.in_flight, detach=bool(detach),
            )
        if detach and not getattr(b, "_detach_watch", False):
            b._detach_watch = True
            threading.Thread(
                target=self._drain_watch, args=(b,),
                name=f"shifu-fleet-drain-{b.addr}", daemon=True,
            ).start()
        return {
            "draining": b.addr,
            "in_flight": b.in_flight,
            "already_draining": already,
            "detach": bool(detach),
        }

    def resume(self, target: str) -> dict:
        """Un-drain ``target`` (the ``POST /drainz {"resume": true}``
        admin verb): new work routes there again. The inverse of
        ``drain(detach=False)``; a DETACHED backend cannot resume —
        re-attach by restarting the router with it in the roster."""
        b = self._backend(target)
        if b.detached:
            raise ValueError(
                f"backend {target!r} is detached; resume only undoes a "
                "non-detaching drain (restart the router to re-attach)"
            )
        was_draining = b.draining
        b.draining = False
        if b.routable() and b.breaker.state != CircuitBreaker.OPEN:
            self._g_up.labels(backend=b.addr).set(1.0)
        if was_draining:
            self.flight.record("backend_resumed", backend=b.addr)
        return {"resumed": b.addr, "was_draining": was_draining}

    def attach_backend(self, target: str) -> dict:
        """Admit ``target`` (``host:port``) into the serving set — the
        ``POST /fleetz {"attach": ...}`` admin verb, and the autoscale
        controller's scale-up actuator. Two shapes:

        * the addr was parked earlier (drain-detached): the SAME
          client object is re-admitted — detached/draining cleared,
          gauges re-upped. This is the one path out of detached state
          short of a router restart (``resume`` still refuses it).
        * a new addr: a :class:`BackendClient` is built with the
          roster's config and wired into metrics like a boot-time
          backend.

        Either way the host is probed + its /v1/models and /cachez
        read HERE (synchronous readiness gate — an unreachable host
        raises RuntimeError and leaves the roster unchanged for a new
        addr / parked for an old one), then ``maybe_peer_warm`` runs
        so a stone-cold join takes its first requests with warm
        prefixes (PR 15's promise)."""
        addr = str(target)
        existing = next(
            (x for x in self.backends if x.addr == addr), None
        )
        b = existing
        if b is None:
            cfg = self.backends[0].cfg if self.backends else None
            b = BackendClient(addr, cfg)
        try:
            self.probe_backend(b)
            b.models()
        except BackendError as e:
            raise RuntimeError(
                f"backend {addr} failed the attach readiness gate: {e}"
            ) from e
        b.refresh_cachez()
        was_parked = False
        if existing is None:
            with self._lock:
                self.backends.append(b)
            self._wire_backend(b)
        else:
            was_parked = b.detached or b.draining
            b.detached = False
            b.draining = False
            self._g_up.labels(backend=b.addr).set(
                1.0 if b.routable() else 0.0
            )
        # Re-eligible for bulk warming: a host that left and came back
        # cold gets its peers' chain tips again (still-warm hosts are
        # skipped by maybe_peer_warm's held-digest check anyway).
        self._peer_warmed.discard(addr)
        self._peer_warm_strikes.pop(addr, None)
        warmed = self.maybe_peer_warm()
        self.flight.record(
            "backend_attached", backend=addr,
            was_parked=was_parked, warmed_chains=warmed,
        )
        return {
            "attached": addr,
            "was_parked": was_parked,
            "warmed_chains": warmed,
            "backends": len(self.backends),
        }

    def _drain_watch(self, b: BackendClient) -> None:
        while b.draining and b.in_flight > 0:
            self._sleep(self._drain_poll_s)
        b._detach_watch = False
        if not b.draining:
            return  # resumed mid-watch: stay attached
        b.detached = True
        self.flight.record("backend_detached", backend=b.addr)

    # ------------------------------------------------- rollout state
    _ROLLOUT_EVENTS = frozenset({
        "begin", "wave_start", "backend_updated", "pause", "unpause",
        "reload_failed", "rollback_started", "rollback_backend",
        "abort", "end", "failed",
    })

    def rollout_note(self, event: str, **fields) -> dict:
        """Record one rollout lifecycle event (the ``POST /rolloutz``
        admin verb — the rollout controller, possibly a separate
        process, reports its walk here so the router's /metrics,
        /statz, and flight ring carry the rollout's progress alongside
        the traffic it is steering around)."""
        event = str(event)
        if event not in self._ROLLOUT_EVENTS:
            raise ValueError(
                f"unknown rollout event {event!r} "
                f"(known: {sorted(self._ROLLOUT_EVENTS)})"
            )
        with self._lock:
            if event == "begin":
                self._rollout = {
                    "status": "running",
                    "ckpt": fields.get("ckpt"),
                    "backends": fields.get("backends"),
                    "updated": [],
                    "rolled_back": [],
                    "paused_reasons": [],
                    "events": 0,
                }
            r = self._rollout
            if r is None:
                raise ValueError(
                    f"rollout event {event!r} before 'begin'"
                )
            r["events"] += 1
            if event == "backend_updated" and fields.get("backend"):
                r["updated"].append(fields["backend"])
            elif event == "rollback_backend" and fields.get("backend"):
                r["rolled_back"].append(fields["backend"])
            elif event == "pause":
                r["status"] = "paused"
                r["paused_reasons"] = list(fields.get("reasons", ()))
            elif event == "unpause":
                r["status"] = "running"
            elif event == "abort":
                r["status"] = "aborted"
            elif event == "failed":
                r["status"] = "failed"
                r["error"] = fields.get("error")
            elif event == "end":
                r["status"] = "complete"
            active = r["status"] in ("running", "paused")
            n_updated = len(r["updated"])
            paused = r["status"] == "paused"
        self._c_rollout_events.labels(event=event).inc()
        self._g_rollout_active.set(1.0 if active else 0.0)
        self._g_rollout_updated.set(float(n_updated))
        self._g_rollout_paused.set(1.0 if paused else 0.0)
        self.flight.record("rollout_" + event, **fields)
        return {"recorded": event}

    def rollout_stats(self) -> Optional[dict]:
        """The /statz rollout block: the current/last rollout's state
        document, or None before any rollout touched this router."""
        with self._lock:
            return dict(self._rollout) if self._rollout else None

    # ----------------------------------------------- autoscale state
    _AUTOSCALE_EVENTS = frozenset({
        "begin", "scale_up", "scale_up_failed", "scale_down",
        "role_flip", "role_flip_failed", "envelope", "end",
    })

    def autoscale_note(self, event: str, **fields) -> dict:
        """Record one autoscale control-loop event (the ``POST
        /autoscalez`` admin verb — the elastic-fleet controller,
        possibly a separate process, reports every decision here so
        the router's /metrics, /statz, and flight ring carry the
        fleet's reshaping alongside the traffic driving it).

        Well-known fields: ``pool`` (active serving-set size — tracked
        on every event that carries it), ``backend``, ``role``/``was``
        (role flips), ``scale``/``util`` (envelope pushes),
        ``headroom`` (min per-tier SLO headroom at decision time),
        ``error`` (the *_failed events)."""
        event = str(event)
        if event not in self._AUTOSCALE_EVENTS:
            raise ValueError(
                f"unknown autoscale event {event!r} "
                f"(known: {sorted(self._AUTOSCALE_EVENTS)})"
            )
        with self._lock:
            if event == "begin":
                self._autoscale = {
                    "status": "running",
                    "standby": list(fields.get("standby") or ()),
                    "pool": fields.get("pool"),
                    "last_action": None,
                    "last_error": None,
                    "headroom": None,
                    "envelope": None,
                    "actions": {
                        "scale_up": 0, "scale_up_failed": 0,
                        "scale_down": 0, "role_flip": 0,
                        "role_flip_failed": 0, "envelope": 0,
                    },
                    "events": 0,
                }
            a = self._autoscale
            if a is None:
                raise ValueError(
                    f"autoscale event {event!r} before 'begin'"
                )
            a["events"] += 1
            if event in a["actions"]:
                a["actions"][event] += 1
                a["last_action"] = {
                    "action": event,
                    **{k: v for k, v in fields.items()
                       if k in ("backend", "role", "was", "scale",
                                "util", "headroom", "error", "tier")},
                }
            if fields.get("pool") is not None:
                a["pool"] = fields["pool"]
            if fields.get("headroom") is not None:
                a["headroom"] = fields["headroom"]
            if event == "envelope":
                a["envelope"] = {
                    "util": fields.get("util"),
                    "scale": fields.get("scale"),
                }
            if event.endswith("_failed"):
                a["last_error"] = fields.get("error")
            if event == "end":
                a["status"] = "stopped"
            active = a["status"] == "running"
            pool = a.get("pool")
        if event in ("scale_up", "scale_up_failed", "scale_down",
                     "role_flip", "role_flip_failed", "envelope"):
            self._c_autoscale_actions.labels(action=event).inc()
        if event == "role_flip":
            self._c_role_flips.inc()
        if event == "envelope":
            if fields.get("util") is not None:
                self._g_envelope_util.set(float(fields["util"]))
            if fields.get("scale") is not None:
                self._g_envelope_scale.set(float(fields["scale"]))
        self._g_autoscale_active.set(1.0 if active else 0.0)
        if pool is not None:
            self._g_autoscale_pool.set(float(pool))
        self.flight.record("autoscale_" + event, **fields)
        return {"recorded": event}

    def autoscale_stats(self) -> Optional[dict]:
        """The /statz autoscale block: the controller's running state
        document (pool size, last action, per-action counts, last
        envelope push), or None before any controller attached."""
        with self._lock:
            return dict(self._autoscale) if self._autoscale else None
