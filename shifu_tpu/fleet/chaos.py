"""Fault injection for fleet chaos testing and the loadgen chaos track.

Two surfaces, one module:

**Server-side fault hooks** (:class:`FaultSpec` +
:func:`install_fault_hooks`) — the deterministic failure injectors the
two-process fleet tests drive via ``FLEET_BACKEND_FAULT_*`` env vars
(tests/_fleet_backend.py reads them with :func:`faults_from_env` and
installs them on its real HTTP server). Each hook makes one failure
path reproducible instead of waiting for the network to misbehave:

  * ``drop_nth`` (``FLEET_BACKEND_FAULT_DROP_NTH=N``) — the Nth
    ``/v1/completions`` request has its connection severed before any
    response bytes (the router's failed-before-first-delta
    resubmission path).
  * ``slow_probe_s`` (``FLEET_BACKEND_FAULT_SLOW_PROBE=S``) — every
    ``/healthz`` answer is delayed S seconds (probe timeouts, prober
    failure backoff).
  * ``reload_fail`` (``FLEET_BACKEND_FAULT_RELOAD_FAIL=1``) — every
    ``POST /reloadz`` 503s without touching the weights (the rollout
    controller's halt-and-resume-on-old-weights path).
  * ``kill_after`` (``FLEET_BACKEND_FAULT_KILL_AFTER=N``) — the
    process SIGKILLs itself right after answering its Nth completion:
    a kill *schedule* the parent does not have to time, so "backend
    dies mid-run" is deterministic in request counts, not seconds.

**Scheduled chaos track** (:class:`ChaosEvent` + :class:`ChaosTrack`)
— the loadgen timeline's fault choreography. A scenario declares
events at offsets into the run (``{"at_s": 10, "action": "kill",
"target": "127.0.0.1:8101"}``); the track executes them against a
live fleet while the generator drives traffic: ``kill`` SIGKILLs a
backend process (pid supplied by the operator — the router only knows
addresses), ``drain``/``resume`` flip a backend via the router's
``/drainz``, and ``rollout`` runs a full rolling weight update through
:class:`~shifu_tpu.fleet.rollout.RolloutController` mid-run. Every
execution counts into ``shifu_loadgen_chaos_events_total`` and leaves
a flight-ring event, so a chaos run's verdict report can show exactly
what was done to the fleet and when. Clock/sleep/action executors are
injectable — the unit tests run the whole schedule on a fake clock
with fake executors, no fleet and no sleeps.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

ENV_PREFIX = "FLEET_BACKEND_FAULT_"

CHAOS_ACTIONS = ("kill", "drain", "resume", "rollout")


# ------------------------------------------------- server-side hooks
@dataclasses.dataclass
class FaultSpec:
    """Declarative server-side fault selection (all off by default)."""

    drop_nth: int = 0
    slow_probe_s: float = 0.0
    reload_fail: bool = False
    kill_after: int = 0

    def active(self) -> bool:
        return bool(
            self.drop_nth or self.slow_probe_s
            or self.reload_fail or self.kill_after
        )


def faults_from_env(env=None) -> FaultSpec:
    """The ``FLEET_BACKEND_FAULT_*`` env contract -> :class:`FaultSpec`
    (the spawned test backends' configuration channel)."""
    env = env if env is not None else os.environ
    return FaultSpec(
        drop_nth=int(env.get(ENV_PREFIX + "DROP_NTH", "0")),
        slow_probe_s=float(env.get(ENV_PREFIX + "SLOW_PROBE", "0")),
        reload_fail=bool(int(env.get(ENV_PREFIX + "RELOAD_FAIL", "0"))),
        kill_after=int(env.get(ENV_PREFIX + "KILL_AFTER", "0")),
    )


def install_fault_hooks(server, spec: Optional[FaultSpec] = None) -> bool:
    """Wrap ``server``'s handler class with the selected chaos hooks
    (subclass + swap — ``make_server``'s handler stays untouched).
    Returns True when any hook was installed."""
    spec = spec if spec is not None else faults_from_env()
    if not spec.active():
        return False
    import socket

    base = server.RequestHandlerClass
    counter = itertools.count(1)

    class FaultyHandler(base):
        def _handle_completions(self, chat):
            n = next(counter)
            if spec.drop_nth and n == spec.drop_nth:
                # Sever before any response bytes: the client (the
                # fleet router) sees a clean transport failure with
                # the request still invisible to ITS caller, so it
                # must resubmit.
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True
                return
            out = super()._handle_completions(chat)
            if spec.kill_after and n >= spec.kill_after:
                # The response above is fully written: the caller saw
                # a clean success, the NEXT request finds a corpse —
                # the deterministic "died between requests" shape.
                os.kill(os.getpid(), signal.SIGKILL)
            return out

        def do_GET(self):
            if spec.slow_probe_s and self.path == "/healthz":
                time.sleep(spec.slow_probe_s)
            return super().do_GET()

        def _handle_reload(self):
            if spec.reload_fail:
                self._send(503, {
                    "error": "injected reload failure (chaos hook)",
                    "reloaded": False,
                })
                return
            return super()._handle_reload()

    server.RequestHandlerClass = FaultyHandler
    return True


# -------------------------------------------------- scheduled track
@dataclasses.dataclass
class ChaosEvent:
    """One scheduled fault: ``action`` at ``at_s`` seconds into the
    run. ``target`` is a backend address for kill/drain/resume;
    ``args`` carries action extras (``pid`` for kill, ``ckpt`` +
    optional controller knobs for rollout)."""

    at_s: float
    action: str
    target: Optional[str] = None
    args: Dict[str, object] = dataclasses.field(default_factory=dict)


def parse_chaos_events(docs) -> List[ChaosEvent]:
    """Scenario ``chaos`` list -> validated, time-sorted events.
    Raises ValueError with every problem collected (not just the
    first) so ``loadgen --check`` reports the full damage."""
    if docs is None:
        return []
    if not isinstance(docs, (list, tuple)):
        raise ValueError("chaos must be a list of event objects")
    events, problems = [], []
    for i, doc in enumerate(docs):
        if not isinstance(doc, dict):
            problems.append(f"chaos[{i}]: not an object")
            continue
        action = doc.get("action")
        if action not in CHAOS_ACTIONS:
            problems.append(
                f"chaos[{i}]: unknown action {action!r} "
                f"(want one of {', '.join(CHAOS_ACTIONS)})"
            )
            continue
        try:
            at_s = float(doc.get("at_s", -1))
        except (TypeError, ValueError):
            at_s = -1.0
        if at_s < 0:
            problems.append(f"chaos[{i}]: at_s must be a number >= 0")
            continue
        target = doc.get("target")
        args = {
            k: v for k, v in doc.items()
            if k not in ("at_s", "action", "target")
        }
        if action in ("drain", "resume", "kill") and not target:
            problems.append(f"chaos[{i}]: {action} requires a target "
                            "backend address")
            continue
        if action == "rollout" and not args.get("ckpt"):
            problems.append(f"chaos[{i}]: rollout requires a ckpt")
            continue
        events.append(ChaosEvent(
            at_s=at_s, action=str(action),
            target=str(target) if target else None, args=args,
        ))
    if problems:
        raise ValueError("; ".join(problems))
    return sorted(events, key=lambda e: e.at_s)


class ChaosTrack:
    """Execute a chaos schedule against a live fleet on its own
    thread. ``pids`` maps backend address -> OS pid (the kill action's
    ammunition — only the process's parent knows it). ``actions`` maps
    action name -> ``callable(event)`` and overrides the default
    executors (the unit tests inject fakes and run the schedule on a
    fake clock). Executions append ``{"at_s", "action", "target",
    "outcome", "t_s"}`` rows to ``executed`` — the verdict report's
    chaos ledger."""

    def __init__(self, events: List[ChaosEvent], *,
                 url: Optional[str] = None,
                 pids: Optional[Dict[str, int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 actions: Optional[Dict[str, Callable]] = None,
                 metrics=None, flight=None):
        from shifu_tpu import obs as _obs

        self.events = sorted(events, key=lambda e: e.at_s)
        self.url = url.rstrip("/") if url else None
        self.pids = dict(pids or {})
        self.clock = clock
        self.sleep = sleep
        self.actions = dict(actions or {})
        self.flight = flight if flight is not None else _obs.FLIGHT
        reg = metrics if metrics is not None else _obs.REGISTRY
        self._c_events = reg.counter(
            "shifu_loadgen_chaos_events_total",
            "Chaos-track events executed during a loadgen run",
            labelnames=("action", "outcome"),
        )
        self.executed: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    # --------------------------------------------------- lifecycle
    def start(self, t0: Optional[float] = None) -> None:
        if not self.events:
            return
        t0 = self.clock() if t0 is None else t0
        self._thread = threading.Thread(
            target=self.run_events, args=(t0,),
            name="shifu-chaos-track", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout_s: float = 30.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def run_events(self, t0: float) -> None:
        """The schedule loop (public so fake-clock tests can run it
        inline, no thread)."""
        self._t0 = t0
        for ev in self.events:
            while not self._stop.is_set():
                wait = t0 + ev.at_s - self.clock()
                if wait <= 0:
                    break
                self.sleep(min(wait, 0.05))
            if self._stop.is_set():
                return
            self._execute(ev)

    # --------------------------------------------------- execution
    def _execute(self, ev: ChaosEvent) -> None:
        fn = self.actions.get(ev.action) or getattr(
            self, "_do_" + ev.action
        )
        try:
            fn(ev)
            outcome = "ok"
        except Exception as e:  # noqa: BLE001 — chaos must not kill the run
            outcome = f"error:{type(e).__name__}"
        self._c_events.labels(action=ev.action, outcome=(
            "ok" if outcome == "ok" else "error"
        )).inc()
        self.flight.record(
            "chaos_" + ev.action, target=ev.target, outcome=outcome,
        )
        self.executed.append({
            "at_s": ev.at_s, "action": ev.action, "target": ev.target,
            "outcome": outcome, "t_s": round(self.clock() - self._t0, 3),
        })

    def _do_kill(self, ev: ChaosEvent) -> None:
        pid = ev.args.get("pid", self.pids.get(ev.target))
        if pid is None:
            raise ValueError(
                f"no pid known for backend {ev.target!r} "
                "(pass pids= or a pid arg on the event)"
            )
        os.kill(int(pid), signal.SIGKILL)

    def _admin(self):
        from shifu_tpu.fleet.rollout import RouterAdmin

        if self.url is None:
            raise ValueError("chaos drain/resume/rollout need a "
                             "router url")
        return RouterAdmin(self.url)

    def _do_drain(self, ev: ChaosEvent) -> None:
        self._admin().drain(ev.target)

    def _do_resume(self, ev: ChaosEvent) -> None:
        self._admin().resume(ev.target)

    def _do_rollout(self, ev: ChaosEvent) -> None:
        from shifu_tpu.fleet.rollout import RolloutController

        ctl = RolloutController(
            self._admin(), str(ev.args["ckpt"]),
            max_unavailable=int(ev.args.get("max_unavailable", 1)),
            drain_timeout_s=float(ev.args.get("drain_timeout_s", 30.0)),
            ready_timeout_s=float(ev.args.get("ready_timeout_s", 30.0)),
        )
        report = ctl.run()
        if report.get("status") != "complete":
            raise RuntimeError(
                f"mid-run rollout did not complete: "
                f"{report.get('status')}"
            )
