"""Declarative serving envelope: pace batch backfill against measured
resource headroom instead of a fixed queue-depth cap.

PR 6-7 bounded the batch tier with ``--batch-backlog N`` — a static
queue-depth cap that knows nothing about WHY the fleet is loaded. The
envelope replaces that guesswork with two measured signals every host
already exposes:

  * **HBM high-water fraction** — device bytes-in-use over bytes-limit
    (the ``shifu_hbm_*`` gauge family; ``/healthz`` carries the pooled
    fraction as ``hbm_frac_used``). Backfill that pushes HBM past the
    high-water mark is backfill about to evict live prefix pages or
    OOM a compile.
  * **Step-time proxy for power** — the interactive tier's p50
    inter-token latency (``/healthz``'s latency block). Decode step
    time rising above the declared ceiling means the chip is saturated
    (and, on TPU, drawing near its power envelope); batch admissions
    are the first load to shed.

The arithmetic is deliberately tiny and pure (fake-clock/unit tested
with no HTTP anywhere): ``utilization`` folds the measured signals
into one worst-dimension fraction of the declared budget, and
``admission_fraction`` maps that to a batch-admission scale — 1.0
(admit freely) below ``ramp``, linear down to 0.0 (shed all backfill)
at the high-water mark. The autoscale controller pushes the scale to
the fleet front-end via ``POST /envelopez``, where it multiplies the
server's batch backlog cap (infer/server.py batch admission).

**Scrape gaps fail safe**: a signal nobody measured (CPU hosts report
no HBM; a fleet with no traffic has no ITL yet) contributes nothing,
and when NO signal is measured ``utilization`` answers None — the
controller then holds the last pushed scale instead of flapping the
throttle on missing data.

Spec syntax (the ``--envelope`` flag)::

    hbm=0.85,step_ms=120          # either part optional
    hbm=0.9,step_ms=80,ramp=0.7   # start shedding at 70% utilization
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

__all__ = ["Envelope", "parse_envelope_spec"]


@dataclass(frozen=True)
class Envelope:
    """A declared serving envelope; see module docstring.

    ``hbm_frac`` — HBM high-water mark as a fraction of bytes-limit in
    (0, 1]; None disables the HBM dimension. ``step_ms`` — decode
    step-time (interactive p50 ITL) ceiling in ms; None disables the
    power-proxy dimension. ``ramp`` — utilization fraction where
    batch-admission throttling starts (1.0 admission below it, linear
    to 0.0 at utilization 1.0)."""

    hbm_frac: Optional[float] = None
    step_ms: Optional[float] = None
    ramp: float = 0.8

    def __post_init__(self):
        if self.hbm_frac is not None and not (0.0 < self.hbm_frac <= 1.0):
            raise ValueError(
                f"envelope hbm fraction must be in (0, 1], got "
                f"{self.hbm_frac} — e.g. hbm=0.85"
            )
        if self.step_ms is not None and not self.step_ms > 0.0:
            raise ValueError(
                f"envelope step_ms must be > 0, got {self.step_ms} — "
                "e.g. step_ms=120"
            )
        if not (0.0 < self.ramp < 1.0):
            raise ValueError(
                f"envelope ramp must be in (0, 1), got {self.ramp} — "
                "e.g. ramp=0.8"
            )
        if self.hbm_frac is None and self.step_ms is None:
            raise ValueError(
                "envelope declares no dimension — give hbm=FRAC "
                "and/or step_ms=MS"
            )

    def utilization(self, *, hbm_frac_used: Optional[float] = None,
                    step_ms_now: Optional[float] = None
                    ) -> Optional[float]:
        """Worst-dimension fraction of the declared budget (1.0 = AT
        the high-water mark; may exceed 1.0). A dimension with no
        measurement — or none declared — contributes nothing; None
        when NOTHING was measured (the scrape-gap hold signal)."""
        dims = []
        if self.hbm_frac is not None and hbm_frac_used is not None:
            if hbm_frac_used >= 0.0:
                dims.append(float(hbm_frac_used) / self.hbm_frac)
        if self.step_ms is not None and step_ms_now is not None:
            if step_ms_now >= 0.0:
                dims.append(float(step_ms_now) / self.step_ms)
        return max(dims) if dims else None

    def admission_fraction(self, util: Optional[float]) -> float:
        """Batch-admission scale in [0, 1] for one utilization sample:
        1.0 below ``ramp``, 0.0 at/over the high-water mark (util
        1.0), linear between. An unmeasured utilization (None) admits
        freely — throttling on missing data would turn every scrape
        gap into a fleet-wide batch stall."""
        if util is None or util <= self.ramp:
            return 1.0
        if util >= 1.0:
            return 0.0
        return (1.0 - util) / (1.0 - self.ramp)

    @staticmethod
    def scaled_cap(base_cap: int, scale: float) -> int:
        """The effective batch backlog cap for one admission scale
        (floor of base*scale, never negative — scale 0.0 means cap 0:
        every batch arrival 429s until the envelope recovers)."""
        return max(0, int(float(base_cap) * min(max(scale, 0.0), 1.0)))


def parse_envelope_spec(spec: str) -> Envelope:
    """``"hbm=0.85,step_ms=120[,ramp=0.8]"`` -> :class:`Envelope`.
    Raises ValueError with a one-line fix hint on junk (the
    ``fleet autoscale --check`` gate surfaces these verbatim)."""
    if not spec or not str(spec).strip():
        raise ValueError(
            "empty envelope spec — e.g. --envelope hbm=0.85,step_ms=120"
        )
    kw = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip()
        if not sep or key not in ("hbm", "step_ms", "ramp"):
            raise ValueError(
                f"envelope part {part!r} is not hbm=/step_ms=/ramp= — "
                "e.g. hbm=0.85,step_ms=120"
            )
        try:
            fval = float(val)
        except ValueError:
            raise ValueError(
                f"envelope {key}={val!r} is not a number — "
                "e.g. hbm=0.85,step_ms=120"
            ) from None
        kw["hbm_frac" if key == "hbm" else key] = fval
    return Envelope(**kw)
