"""Client for ONE remote engine host, speaking the engine HTTP surface.

The inter-host protocol is deliberately the protocol that already
exists: ``POST /v1/completions`` with ``stream: true`` (SSE deltas +
one definitive final event), ``GET /healthz`` (the uniform
counters/latency/status document), ``GET /metrics`` (Prometheus text).
Cancellation is connection close — the backend server already treats a
dropped SSE client as a cancel and frees the slot (infer/server.py), so
the fleet needs no new cancel verb on the wire.

Failure machinery, all deterministic-clock injectable for tests
(tests/test_fleet_retry.py drives every transition without a sleep):

  * per-call timeouts — connect/submit and stream-read are separate
    budgets (a slow decode is not a dead host);
  * :class:`RetryPolicy` — capped exponential backoff with jitter and
    a token-bucket RETRY BUDGET shared across the fleet: each retry
    spends a token, each success refills a fraction, and an empty
    bucket fails fast (:class:`FleetUnavailable`, surfaced by the
    router's server as a 503 with ``Retry-After``) instead of letting
    a dying fleet drown in retry storms;
  * :class:`CircuitBreaker` — trips OPEN on N consecutive failures
    (stops routing instantly instead of timing out per request),
    half-opens after a cooldown to admit one probe, and closes again
    on probe success. Transitions invoke an ``on_transition`` hook the
    router wires to ``backend_down``/``backend_up`` flight events and
    the ``shifu_fleet_breaker_state`` gauge.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Callable, Iterator, Optional, Tuple


class BackendError(RuntimeError):
    """A backend call failed. ``retryable`` says whether another
    backend (or another attempt) could still serve the request —
    transport faults and engine deaths are retryable, validation
    rejections (HTTP 4xx, non-retryable error events) are not."""

    def __init__(self, msg: str, *, retryable: bool, status: Optional[int] = None):
        super().__init__(msg)
        self.retryable = retryable
        self.status = status


class FleetUnavailable(RuntimeError):
    """No backend can take the request (all breakers open / roster
    drained / retry budget exhausted). The serving front-end maps this
    onto ``503`` with a ``Retry-After`` header (infer/server.py)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after = max(1, int(round(retry_after_s)))


class RetryPolicy:
    """Capped exponential backoff with jitter + a token-bucket budget.

    ``delay(attempt)`` for attempt k (0-based) draws uniformly from
    ``[(1 - jitter) * d, d]`` with ``d = min(cap_s, base_s * 2**k)`` —
    capped growth, and jitter so a fleet of retriers does not
    synchronise. ``spend()`` takes one token from the budget (False
    when empty — the caller must fail fast); ``refund()`` credits
    ``refill`` of a token, called per SUCCESSFUL request, so a healthy
    fleet regains headroom but a permanently failing one cannot retry
    forever. Thread-safe; ``rng`` is injectable for deterministic
    tests.
    """

    def __init__(self, *, base_s: float = 0.05, cap_s: float = 2.0,
                 jitter: float = 0.5, budget: float = 8.0,
                 refill: float = 0.1, rng: Optional[Callable[[], float]] = None):
        if not (0.0 <= jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"need 0 < base_s <= cap_s, got {base_s}/{cap_s}")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter = float(jitter)
        self.budget_max = float(budget)
        self.refill = float(refill)
        self._budget = float(budget)
        self._rng = rng if rng is not None else random.random
        self._lock = threading.Lock()

    @property
    def budget(self) -> float:
        return self._budget

    def delay(self, attempt: int) -> float:
        d = min(self.cap_s, self.base_s * (2.0 ** max(0, int(attempt))))
        return d * (1.0 - self.jitter * self._rng())

    def spend(self) -> bool:
        with self._lock:
            if self._budget < 1.0:
                return False
            self._budget -= 1.0
            return True

    def refund(self) -> None:
        with self._lock:
            self._budget = min(self.budget_max, self._budget + self.refill)


class CircuitBreaker:
    """closed -> (N consecutive failures) -> open -> (cooldown)
    -> half_open -> (probe success) -> closed | (probe failure) -> open.

    ``allow()`` is the routing gate: always True closed, False while
    open and cooling, and True exactly ONCE per cooldown expiry (the
    half-open probe) — concurrent callers see False until that probe
    resolves. ``clock`` is injectable (monotonic seconds) so the
    trip/half-open/close walk is testable without sleeping.
    """

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    # Gauge encoding for shifu_fleet_breaker_state.
    STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, *, fail_threshold: int = 3, reset_s: float = 5.0,
                 clock: Optional[Callable[[], float]] = None,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        if fail_threshold < 1:
            raise ValueError(f"fail_threshold must be >= 1, got {fail_threshold}")
        self.fail_threshold = int(fail_threshold)
        self.reset_s = float(reset_s)
        self._clock = clock if clock is not None else time.monotonic
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._fails = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        # Surface "open past cooldown" as open still — the state only
        # advances through allow() (the probe admission point).
        return self._state

    def _move(self, new: str) -> None:
        old, self._state = self._state, new
        if old != new and self.on_transition is not None:
            self.on_transition(old, new)

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_s:
                    self._move(self.HALF_OPEN)
                    self._probing = True
                    return True
                return False
            # half_open: one outstanding probe at a time.
            if not self._probing:
                self._probing = True
                return True
            return False

    def cooldown_remaining(self) -> float:
        """Seconds until an OPEN breaker would admit its half-open
        probe (0.0 when it is due now, or when not open). The prober's
        backoff consults this so a backed-off dead host still gets its
        half-open trial ON SCHEDULE — backoff must never delay the
        breaker walk (fleet/bootstrap.py)."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(
                0.0, self.reset_s - (self._clock() - self._opened_at)
            )

    def record_success(self) -> None:
        with self._lock:
            self._fails = 0
            self._probing = False
            self._move(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == self.HALF_OPEN:
                self._opened_at = self._clock()
                self._move(self.OPEN)
                return
            self._fails += 1
            if self._fails >= self.fail_threshold:
                self._opened_at = self._clock()
                self._fails = 0
                self._move(self.OPEN)


class BackendConfig:
    """Per-backend call budgets + failure thresholds (one config object
    shared by the roster; plain attributes, no dataclass magic so tests
    can tweak freely)."""

    def __init__(self, *, connect_timeout_s: float = 5.0,
                 probe_timeout_s: float = 3.0,
                 read_timeout_s: float = 300.0,
                 fail_threshold: int = 3, reset_s: float = 5.0,
                 ewma_alpha: float = 0.2):
        self.connect_timeout_s = float(connect_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.read_timeout_s = float(read_timeout_s)
        self.fail_threshold = int(fail_threshold)
        self.reset_s = float(reset_s)
        self.ewma_alpha = float(ewma_alpha)


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(f"backend address {addr!r} is not host:port")
    return host, int(port)


class _SSEStream:
    """One open streaming completion on a backend: iterate events,
    ``close()`` from any thread to cancel (the backend server frees
    the slot on disconnect). Yields parsed ``data:`` JSON objects and
    stops at ``[DONE]``."""

    def __init__(self, conn: http.client.HTTPConnection, resp, sock):
        self._conn = conn
        self._resp = resp
        # The socket is captured BEFORE getresponse(): the server's
        # ``Connection: close`` makes http.client detach ``conn.sock``
        # there, while the response keeps its own fd reference.
        self._sock = sock
        self._closed = False

    def close(self) -> None:
        self._closed = True
        # shutdown(), not just close(): the response object holds its
        # own reference to the fd (sock.makefile), so close() alone
        # would leave the TCP connection fully open — the backend
        # would never see the disconnect-cancel, and a reader thread
        # blocked in recv() would not wake. SHUT_RDWR sends the FIN
        # (the backend's cancel signal) AND unblocks the reader.
        try:
            if self._sock is not None:
                self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._resp.close()
        except Exception:
            pass
        try:
            self._conn.close()
        except Exception:
            pass

    def __iter__(self) -> Iterator[dict]:
        buf = b""
        try:
            while True:
                chunk = self._resp.readline()
                if not chunk:
                    raise BackendError(
                        "backend connection closed mid-stream",
                        retryable=True,
                    )
                line = chunk.strip()
                if not line:
                    continue
                if not line.startswith(b"data:"):
                    continue
                buf = line[len(b"data:"):].strip()
                if buf == b"[DONE]":
                    return
                try:
                    yield json.loads(buf)
                except ValueError:
                    raise BackendError(
                        f"unparseable SSE event: {buf[:200]!r}",
                        retryable=True,
                    ) from None
        except (OSError, http.client.HTTPException) as e:
            if self._closed:
                return  # deliberate cancel, not a backend fault
            raise BackendError(
                f"backend stream failed: {e!r}", retryable=True
            ) from e
        finally:
            self.close()


class BackendClient:
    """One remote engine host: typed calls over its HTTP surface plus
    the local failure state (breaker, EWMA latency, cached /healthz).

    The router owns routing policy; this class owns the wire. All
    mutable fields that routing reads (``in_flight``, ``health``,
    ``ewma_ms``) are plain attributes updated under the GIL — the same
    single-writer tolerance the metrics registry documents.
    """

    def __init__(self, addr: str, cfg: Optional[BackendConfig] = None, *,
                 clock: Optional[Callable[[], float]] = None,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.addr = addr
        self.host, self.port = _parse_addr(addr)
        self.cfg = cfg if cfg is not None else BackendConfig()
        self.breaker = CircuitBreaker(
            fail_threshold=self.cfg.fail_threshold,
            reset_s=self.cfg.reset_s, clock=clock,
            on_transition=on_transition,
        )
        # Router-visible state.
        self.in_flight = 0          # requests this router is running here
        self.routed = 0             # requests ever routed here
        self.retries = 0            # failures here that caused a retry
        self.draining = False       # no NEW work; in-flight finishes
        self.detached = False       # drained to zero and released
        self._detach_watch = False  # a drain-detach watcher is running
        self.health: Optional[dict] = None   # last /healthz document
        self.health_ts: Optional[float] = None
        self.ewma_ms: Optional[float] = None  # EWMA routed-request wall ms
        self.max_len: Optional[int] = None    # from /v1/models at attach
        # Model-aware routing surface (both from /v1/models): the model
        # ids this backend serves (requests naming one route only to
        # backends listing it) and the checkpoint path it reports
        # serving (the rollout controller's rollback anchor).
        self.model_ids: Optional[list] = None
        self.ckpt: Optional[str] = None
        # Disaggregation role ("prefill" | "decode" | "both"), learned
        # from /healthz + /v1/models at probe time — the router's
        # phase-aware scheduling key. "both" until the host says
        # otherwise (every pre-disagg backend is colocated).
        self.role: str = "both"
        # Last /cachez document (refreshed by the prober alongside the
        # /healthz probe) — the sticky router's cache-pressure signal
        # and its "can this host export/ingest KV?" gate, read off the
        # hot path instead of a per-request scrape.
        self.cache: Optional[dict] = None
        self.cache_ts: Optional[float] = None

    # ------------------------------------------------------------- wire
    def _request(self, method: str, path: str, body,
                 timeout: float, headers: Optional[dict] = None):
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=timeout
        )
        if isinstance(body, (bytes, bytearray)):
            # Raw frame (the SKVP page payload POST) — not JSON.
            payload = bytes(body)
            hdrs = {"Content-Type": "application/octet-stream"}
        else:
            payload = json.dumps(body).encode() if body is not None else None
            hdrs = {"Content-Type": "application/json"} if payload else {}
        hdrs.update(headers or {})
        try:
            conn.request(method, path, payload, hdrs)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise BackendError(
                f"backend {self.addr} unreachable: {e!r}", retryable=True
            ) from e
        return conn, resp

    def _call_json(self, method: str, path: str, body: Optional[dict],
                   timeout: float) -> dict:
        conn, resp = self._request(method, path, body, timeout)
        try:
            data = resp.read()
            if resp.status >= 500:
                raise BackendError(
                    f"backend {self.addr} {path} -> {resp.status}: "
                    f"{data[:200]!r}", retryable=True, status=resp.status,
                )
            if resp.status >= 400:
                msg = data.decode("utf-8", "replace")
                try:
                    msg = json.loads(msg).get("error", msg)
                except ValueError:
                    pass
                raise BackendError(msg, retryable=False, status=resp.status)
            return json.loads(data)
        except (OSError, ValueError, http.client.HTTPException) as e:
            raise BackendError(
                f"backend {self.addr} {path} failed: {e!r}", retryable=True
            ) from e
        finally:
            conn.close()

    def probe(self) -> dict:
        """GET /healthz with the probe timeout; caches the document and
        drives the breaker (success closes a half-open breaker — this
        IS the half-open probe when the prober calls it). Raises
        :class:`BackendError` on failure."""
        try:
            doc = self._call_json(
                "GET", "/healthz", None, self.cfg.probe_timeout_s
            )
        except BackendError:
            self.breaker.record_failure()
            raise
        self.health = doc
        self.health_ts = time.time()
        if doc.get("role") in ("prefill", "decode", "both"):
            self.role = doc["role"]
        self.breaker.record_success()
        return doc

    def models(self) -> dict:
        """GET /v1/models — caches ``max_len`` (request bounds),
        ``model_ids`` (model-aware routing: the ids this host serves,
        adapters included), and ``ckpt`` (the checkpoint the host
        reports serving — the rollout controller's rollback anchor)."""
        doc = self._call_json(
            "GET", "/v1/models", None, self.cfg.probe_timeout_s
        )
        ids = []
        for m in doc.get("data", ()):
            if isinstance(m.get("id"), str) and m["id"]:
                ids.append(m["id"])
            if m.get("max_len"):
                self.max_len = int(m["max_len"])
            if m.get("ckpt"):
                self.ckpt = str(m["ckpt"])
            if m.get("role") in ("prefill", "decode", "both"):
                self.role = m["role"]
        if ids:
            self.model_ids = ids
        return doc

    def cachez(self) -> dict:
        """GET /cachez — the backend's prefix-cache + host-KV-tier
        occupancy/hit-rate block (the per-backend scrape prefix-aware
        sticky routing reads; the router's own ``cache_stats`` renders
        one block per backend from this). Caches the document like
        ``probe`` caches /healthz."""
        doc = self._call_json(
            "GET", "/cachez", None, self.cfg.probe_timeout_s
        )
        self.cache = doc
        self.cache_ts = time.time()
        return doc

    def refresh_cachez(self) -> None:
        """Best-effort /cachez refresh (prober tick). Failures keep the
        last document — a missed scrape degrades the routing score to
        slightly stale cache pressure, never to an error."""
        try:
            self.cachez()
        except BackendError:
            pass

    def cache_occupancy(self) -> float:
        """Fraction of this host's device prefix pool holding
        registered prefix pages, from the cached /cachez doc (0.0 when
        unknown or the cache is disabled). The sticky score reads this
        as cache PRESSURE: a fuller pool evicts sooner, so new sessions
        prefer emptier hosts."""
        pc = (self.cache or {}).get("prefix_cache") or {}
        try:
            n = int(pc.get("n_pages") or 0)
            reg = int(pc.get("registered_pages") or 0)
        except (TypeError, ValueError):
            return 0.0
        return min(reg / n, 1.0) if n > 0 else 0.0

    def cache_hit_rate(self):
        """Lifetime prefix-cache token hit rate from the cached /cachez
        doc (None when unknown)."""
        pc = (self.cache or {}).get("prefix_cache") or {}
        return pc.get("hit_rate")

    def has_host_tier(self) -> bool:
        """Does this host run the host KV tier — i.e. can it export
        (``kv_export``/GET /kv/pages) and ingest (POST /kv/pages) page
        chains? From the cached /cachez doc; False until scraped."""
        return bool((self.cache or {}).get("host_tier"))

    def reload(self, ckpt: str,
               timeout_s: Optional[float] = None) -> dict:
        """POST /reloadz {"ckpt": ...} — hot-swap this backend's
        serving weights from a checkpoint path visible to the BACKEND
        host. Uses the stream read budget by default (a whole
        checkpoint loads inside this call). A 5xx means the backend
        REFUSED the swap (torn/corrupt checkpoint, structure mismatch)
        and still serves its old weights — the rollout controller stops
        there instead of marching a bad artifact across the fleet."""
        return self._call_json(
            "POST", "/reloadz", {"ckpt": str(ckpt)},
            timeout_s if timeout_s is not None
            else self.cfg.read_timeout_s,
        )

    def rolez(self, role: str,
              timeout_s: Optional[float] = None) -> dict:
        """POST /rolez {"role": ...} — flip this backend's advertised
        disaggregation role (prefill|decode|both). Only legal on an
        idle engine: the server answers 503 while requests are active
        or queued, so the autoscale controller drains the host through
        the router FIRST and only then flips. A non-retryable 4xx means
        the role string was junk; a 5xx means the host refused (still
        busy) and keeps its old role — the controller resumes it
        unflipped and retries a later tick."""
        return self._call_json(
            "POST", "/rolez", {"role": str(role)},
            timeout_s if timeout_s is not None
            else self.cfg.probe_timeout_s,
        )

    def metrics_text(self) -> str:
        """GET /metrics — raw Prometheus text pass-through (operators
        can scrape a backend THROUGH the router's statz links; the
        router's own /metrics stays its own registry)."""
        conn, resp = self._request(
            "GET", "/metrics", None, self.cfg.probe_timeout_s
        )
        try:
            if resp.status != 200:
                raise BackendError(
                    f"backend {self.addr} /metrics -> {resp.status}",
                    retryable=True, status=resp.status,
                )
            return resp.read().decode("utf-8", "replace")
        except (OSError, http.client.HTTPException) as e:
            raise BackendError(
                f"backend {self.addr} /metrics failed: {e!r}",
                retryable=True,
            ) from e
        finally:
            conn.close()

    def debugz(self, n: Optional[int] = None) -> dict:
        """GET /debugz[?n=] — the backend's flight-recorder ring, tail-
        limited to the last ``n`` events when given. Incident-bundle
        captures (obs/incident.py) always pass ``n`` so a fleet-wide
        forensics scrape is bounded per host instead of shipping every
        full ring."""
        path = "/debugz"
        if n is not None:
            path += f"?n={int(n)}"
        return self._call_json(
            "GET", path, None, self.cfg.probe_timeout_s
        )

    def tracez(self, trace_id: str) -> dict:
        """GET /tracez?trace_id=... — the backend's span-store slice
        for one distributed trace (host documents with paired
        monotonic/wall stamps; ``obs.disttrace.merge_host_docs`` aligns
        them onto the collector's clock)."""
        from urllib.parse import quote

        return self._call_json(
            "GET", f"/tracez?trace_id={quote(str(trace_id))}", None,
            self.cfg.probe_timeout_s,
        )

    def kv_pages(self, rid: int,
                 trace_header: Optional[str] = None) -> bytes:
        """GET /kv/pages?rid= — fetch the SKVP frame a prefill host
        exported for one of ITS rids (prefill/decode disaggregation).
        The frame is structurally validated HERE (magic/version/crc via
        ``deserialize_pages``) so a truncated or bit-flipped transfer
        surfaces at the fetch, not as a corrupt decode two hops later.

        EVERY failure — unreachable host, 404 (rid expired), 5xx,
        torn frame — raises a *retryable* :class:`BackendError`: a
        failed handoff is never fatal to the request, the router just
        serves it colocated (cold prefill, PR-5 behavior)."""
        return self._kv_fetch(f"rid={int(rid)}", trace_header)

    def kv_pages_digest(self, digest: str,
                        trace_header: Optional[str] = None) -> bytes:
        """GET /kv/pages?digest= — content-addressed peer fetch: the
        SKVP frame holding the full page chain ending at ``digest``
        (a sha256 chain key this host advertised in its /cachez
        ``digests.held`` block). Same validation and same always-
        retryable failure contract as the rid-keyed fetch — a failed
        peer fetch just means the requester prefills cold."""
        from urllib.parse import quote

        return self._kv_fetch(
            f"digest={quote(str(digest))}", trace_header
        )

    def held_digests(self) -> dict:
        """(digest hex → parent hex | None) this backend advertised in
        its last /cachez scrape — the router folds these into the
        fleet digest map. Empty when unscrapped or tier-less."""
        dg = (self.cache or {}).get("digests") or {}
        out = {}
        for row in dg.get("held") or ():
            try:
                k, parent = row[0], row[1]
            except (IndexError, TypeError):
                continue
            if isinstance(k, str):
                out[k] = parent if isinstance(parent, str) else None
        return out

    def _kv_fetch(self, query: str,
                  trace_header: Optional[str] = None) -> bytes:
        from shifu_tpu.infer.kvtier import (
            WireFormatError, deserialize_pages,
        )

        hdrs = {"x-shifu-trace": trace_header} if trace_header else None
        conn, resp = self._request(
            "GET", f"/kv/pages?{query}", None,
            self.cfg.read_timeout_s, headers=hdrs,
        )
        try:
            data = resp.read()
            if resp.status != 200:
                msg = data.decode("utf-8", "replace")
                try:
                    msg = json.loads(msg).get("error", msg)
                except ValueError:
                    pass
                raise BackendError(
                    f"backend {self.addr} kv fetch -> {resp.status}: "
                    f"{msg}", retryable=True, status=resp.status,
                )
        except (OSError, http.client.HTTPException) as e:
            raise BackendError(
                f"backend {self.addr} kv fetch failed: {e!r}",
                retryable=True,
            ) from e
        finally:
            conn.close()
        try:
            deserialize_pages(data)
        except WireFormatError as e:
            raise BackendError(
                f"backend {self.addr} kv frame rejected: {e}",
                retryable=True,
            ) from e
        return data

    def kv_ingest(self, payload: bytes,
                  trace_header: Optional[str] = None) -> dict:
        """POST /kv/pages — hand a fetched SKVP frame to this (decode)
        host, which deserializes it into its own page pool through the
        prefix-registration path. The decode host re-verifies the crc
        and every leaf shape; ANY refusal (400 included) raises a
        retryable :class:`BackendError` — the router's answer to a
        failed handoff is always a colocated fallback, never an
        error."""
        hdrs = {"x-shifu-trace": trace_header} if trace_header else None
        conn, resp = self._request(
            "POST", "/kv/pages", bytes(payload),
            self.cfg.read_timeout_s, headers=hdrs,
        )
        try:
            data = resp.read()
            if resp.status != 200:
                msg = data.decode("utf-8", "replace")
                try:
                    msg = json.loads(msg).get("error", msg)
                except ValueError:
                    pass
                raise BackendError(
                    f"backend {self.addr} kv ingest -> {resp.status}: "
                    f"{msg}", retryable=True, status=resp.status,
                )
            return json.loads(data)
        except (OSError, ValueError, http.client.HTTPException) as e:
            raise BackendError(
                f"backend {self.addr} kv ingest failed: {e!r}",
                retryable=True,
            ) from e
        finally:
            conn.close()

    def open_stream(self, body: dict,
                    headers: Optional[dict] = None) -> _SSEStream:
        """POST /v1/completions with ``stream: true``; returns the SSE
        event iterator. ``headers`` extends the defaults (the router
        forwards ``x-shifu-trace`` here so the backend's spans join the
        request's distributed trace). The HTTP status is resolved HERE
        (connect + submit under ``connect_timeout_s``); event reads
        then run under ``read_timeout_s`` per read (a slow decode is
        budgeted separately from a dead host)."""
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.cfg.connect_timeout_s
        )
        try:
            conn.request(
                "POST", "/v1/completions", json.dumps(body).encode(),
                {"Content-Type": "application/json", **(headers or {})},
            )
            # Capture the socket NOW: the SSE response carries
            # ``Connection: close``, so getresponse() detaches
            # ``conn.sock`` (the response keeps its own fd reference).
            sock = conn.sock
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise BackendError(
                f"backend {self.addr} unreachable: {e!r}", retryable=True
            ) from e
        if resp.status != 200:
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException):
                data = b""
            finally:
                conn.close()
            msg = data.decode("utf-8", "replace")
            try:
                msg = json.loads(msg).get("error", msg)
            except ValueError:
                pass
            raise BackendError(
                msg or f"backend {self.addr} -> {resp.status}",
                retryable=resp.status >= 500, status=resp.status,
            )
        # Widen the socket budget for the stream phase.
        if sock is not None:
            sock.settimeout(self.cfg.read_timeout_s)
        return _SSEStream(conn, resp, sock)

    # ------------------------------------------------------ router hooks
    def routable(self) -> bool:
        """May NEW work land here? (Breaker consultation is separate —
        ``allow()`` consumes the half-open probe slot, so the router
        only calls it for a backend it is about to use.)"""
        return not self.draining and not self.detached

    def note_latency(self, ms: float) -> None:
        a = self.cfg.ewma_alpha
        self.ewma_ms = (
            ms if self.ewma_ms is None else (1 - a) * self.ewma_ms + a * ms
        )

    def queue_depth(self) -> int:
        """Remote queue depth from the last probe (stale between
        probes; the router's primary load signal is its own live
        ``in_flight``)."""
        if not self.health:
            return 0
        try:
            return int(self.health.get("queued", 0))
        except (TypeError, ValueError):
            return 0

    def status(self) -> str:
        if self.detached:
            return "detached"
        if self.draining:
            return "draining"
        if self.breaker.state == CircuitBreaker.OPEN:
            return "down"
        return "up"


def _jitter_check(policy: RetryPolicy, attempt: int) -> Tuple[float, float]:
    """The [lo, hi] envelope ``delay(attempt)`` must land in — shared
    with tests so the bound and the implementation cannot drift."""
    hi = min(policy.cap_s, policy.base_s * (2.0 ** attempt))
    return hi * (1.0 - policy.jitter), hi
