"""Zero-downtime rolling weight rollout over a live serving fleet.

The composition ROADMAP item 4 asked for: ``POST /drainz`` draining
(PR 5), the engine server's new ``POST /reloadz`` hot-swap, the
readiness gating ``fleet/bootstrap.py`` already does at startup, and
the SLO watchdog's pooled p99 budgets — walked across the roster one
``--max-unavailable`` wave at a time, while live traffic keeps flowing
through the backends that are NOT in the current wave.

Per backend the walk is::

    drain (router stops routing new work; in-flight streams finish)
      -> POST /reloadz {ckpt} (backend loads + verifies + swaps;
         a torn/corrupt checkpoint 503s and the backend KEEPS its old
         weights — the rollout halts instead of marching a bad
         artifact across the fleet)
      -> readiness gate (/healthz healthy + /v1/models reporting the
         target checkpoint, exactly like bootstrap's startup gate)
      -> resume (router routes to it again)

Between waves the controller reads the router's SLO watchdog verdict
(the same pooled p99 TTFT/ITL budgets that guard normal traffic). A
budget breach PAUSES the wave — the fleet keeps serving on however
many backends are already updated — until the verdict clears or
``pause_timeout_s`` expires; with ``abort_on_slo`` a breach instead
rolls every already-updated backend back to the checkpoint it reported
before its swap (drain -> reload(prev) -> gate -> resume, newest
first).

The controller talks to the LIVE router through its HTTP admin surface
(:class:`RouterAdmin`: ``/statz`` for the roster + watchdog verdict,
``/drainz`` with ``detach:false``/``resume:true``, ``/rolloutz`` to
record progress on the router's metrics/flight/statz) and to each
backend directly (``/reloadz``, ``/healthz``, ``/v1/models`` via
:class:`~shifu_tpu.fleet.backend.BackendClient`) — the same split a
human operator would drive with curl. ``admin`` and ``make_backend``
are injectable, so tests walk every pause/abort/rollback path with
fakes and no sockets (tests/test_rollout.py) and the two-process
harness drives the real wire (tests/test_fleet_rollout.py).

CLI: ``shifu_tpu fleet rollout --ckpt PATH --router URL
[--max-unavailable 1] [--abort-on-slo]``.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Tuple

from shifu_tpu.fleet.backend import BackendClient, BackendError


class RolloutError(RuntimeError):
    """The rollout could not proceed (drain stuck, reload refused,
    readiness gate timed out, SLO paused past its budget...). The
    fleet is left SERVING — every backend the controller touched was
    resumed on whatever weights it holds — but possibly mixed-version;
    the report names which backends run what."""


class RouterAdmin:
    """The live router's HTTP admin surface, as the rollout controller
    consumes it. One instance per rollout; stateless between calls."""

    def __init__(self, url: str, *, timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)

    # ------------------------------------------------------------ wire
    def _call(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise RolloutError(
                f"router {method} {path} -> {e.code}: {msg}"
            ) from e
        except (OSError, ValueError) as e:
            raise RolloutError(
                f"router {method} {path} unreachable: {e!r}"
            ) from e

    # --------------------------------------------------------- surface
    def statz(self) -> dict:
        return self._call("GET", "/statz")

    def backends(self) -> List[dict]:
        """The roster rows from the router's /statz fleet block."""
        fleet = self.statz().get("fleet")
        if not fleet or "backends" not in fleet:
            raise RolloutError(
                f"{self.url} serves no fleet block on /statz — is it a "
                "fleet router (`serve --fleet`)?"
            )
        return fleet["backends"]

    def fleet_row(self, addr: str) -> dict:
        row = next(
            (r for r in self.backends() if r.get("backend") == addr),
            None,
        )
        if row is None:
            raise RolloutError(f"backend {addr} left the router roster")
        return row

    def slo(self) -> dict:
        """The watchdog verdict ({"status", "reasons"}) — the rollout's
        automatic brake."""
        return self.statz().get(
            "watchdog", {"status": "ok", "reasons": []}
        )

    def drain(self, addr: str) -> dict:
        return self._call(
            "POST", "/drainz", {"backend": addr, "detach": False}
        )

    def resume(self, addr: str) -> dict:
        return self._call(
            "POST", "/drainz", {"backend": addr, "resume": True}
        )

    def note(self, event: str, **fields) -> None:
        self._call("POST", "/rolloutz", {"event": event, **fields})

    # ---------------------------------------- autoscale verbs (PR 20)
    def sloz(self) -> dict:
        """The router's GET /sloz document — per-tier burn-rate
        headroom, the autoscale controller's primary input."""
        return self._call("GET", "/sloz")

    def attach(self, addr: str) -> dict:
        """POST /fleetz {"attach": addr} — admit a standby host into
        the serving set (the scale-up actuator; also the one path
        back for a parked host). The router probes it synchronously,
        so a dead standby raises :class:`RolloutError` here with the
        roster unchanged."""
        return self._call("POST", "/fleetz", {"attach": addr})

    def park(self, addr: str) -> dict:
        """POST /drainz {"detach": true} — drain ``addr`` and, once
        its in-flight streams finish, detach it from the serving set
        (the scale-down actuator; ``attach`` re-admits it)."""
        return self._call(
            "POST", "/drainz", {"backend": addr, "detach": True}
        )

    def autoscale_note(self, event: str, **fields) -> None:
        self._call("POST", "/autoscalez", {"event": event, **fields})

    def set_envelope(self, scale: float,
                     util: Optional[float] = None) -> dict:
        """POST /envelopez — push the fleet-wide batch-admission scale
        the envelope arithmetic produced (fleet/envelope.py)."""
        body = {"scale": float(scale)}
        if util is not None:
            body["util"] = float(util)
        return self._call("POST", "/envelopez", body)


class RolloutController:
    """Walk a roster through a rolling weight swap; see module
    docstring. ``run()`` returns the report dict (status complete /
    failed / aborted, the per-backend outcomes) and raises
    :class:`RolloutError` only for errors the report cannot express
    (e.g. an unreachable router before anything started)."""

    def __init__(
        self,
        admin: RouterAdmin,
        ckpt: str,
        *,
        max_unavailable: int = 1,
        abort_on_slo: bool = False,
        make_backend: Callable[[str], BackendClient] = BackendClient,
        drain_timeout_s: float = 120.0,
        ready_timeout_s: float = 60.0,
        pause_timeout_s: float = 300.0,
        poll_s: float = 0.1,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        if max_unavailable < 1:
            raise ValueError(
                f"max_unavailable must be >= 1, got {max_unavailable}"
            )
        self.admin = admin
        self.ckpt = str(ckpt)
        self.max_unavailable = int(max_unavailable)
        self.abort_on_slo = bool(abort_on_slo)
        self.make_backend = make_backend
        self.drain_timeout_s = float(drain_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.pause_timeout_s = float(pause_timeout_s)
        self.poll_s = float(poll_s)
        self._clock = clock
        self._sleep = sleep
        # (addr, previous-ckpt-or-None), in update order — the
        # rollback ledger.
        self.updated: List[Tuple[str, Optional[str]]] = []
        self.paused = 0

    # ------------------------------------------------------------- run
    def run(self) -> dict:
        rows = [
            r for r in self.admin.backends()
            if r.get("status") != "detached"
        ]
        addrs = [r["backend"] for r in rows]
        if not addrs:
            raise RolloutError("roster has no attached backends")
        self.admin.note("begin", ckpt=self.ckpt, backends=len(addrs))
        waves = [
            addrs[i:i + self.max_unavailable]
            for i in range(0, len(addrs), self.max_unavailable)
        ]
        try:
            for wave in waves:
                brake = self._slo_brake()
                if brake is not None:
                    return self._abort(brake)
                self.admin.note("wave_start", backends=wave)
                drained: List[str] = []
                try:
                    for addr in wave:
                        self.admin.drain(addr)
                        drained.append(addr)
                    for addr in wave:
                        self._update_one(addr)
                finally:
                    # Whatever happened, nothing in this wave stays
                    # silently drained: _update_one resumes on its own
                    # paths; this catches drain-phase failures.
                    for addr in drained:
                        self._resume_quietly(addr)
        except RolloutError as e:
            self.admin.note("failed", error=str(e))
            return self._report("failed", error=str(e))
        self.admin.note("end", updated=len(self.updated))
        return self._report("complete")

    # ---------------------------------------------------- wave pieces
    def _update_one(self, addr: str) -> None:
        """drain already done; wait idle -> reload -> gate -> resume.
        Raises RolloutError with the backend resumed (old weights) on
        any failure."""
        self._wait_drained(addr)
        b = self.make_backend(addr)
        prev = self._backend_ckpt(b)
        try:
            b.reload(self.ckpt)
        except BackendError as e:
            self._resume_quietly(addr)
            self.admin.note(
                "reload_failed", backend=addr, error=str(e),
                status=e.status,
            )
            raise RolloutError(
                f"backend {addr} refused the reload "
                f"(status {e.status}): {e} — it still serves its old "
                "weights; rollout halted"
            ) from e
        try:
            self._gate_ready(addr, b)
        except RolloutError:
            self._resume_quietly(addr)
            raise
        self.admin.resume(addr)
        self.updated.append((addr, prev))
        self.admin.note("backend_updated", backend=addr, prev=prev)

    def _wait_drained(self, addr: str) -> None:
        deadline = self._clock() + self.drain_timeout_s
        while True:
            row = self.admin.fleet_row(addr)
            if int(row.get("in_flight", 0)) == 0:
                return
            if self._clock() >= deadline:
                self._resume_quietly(addr)
                raise RolloutError(
                    f"backend {addr} still has {row['in_flight']} "
                    f"in-flight streams after {self.drain_timeout_s:g}s "
                    "drain; resumed on old weights"
                )
            self._sleep(self.poll_s)

    def _backend_ckpt(self, b: BackendClient) -> Optional[str]:
        """The checkpoint the backend reports serving (rollback
        anchor); None when the backend predates ckpt reporting or was
        started without --ckpt-dir (rollback then skips it, loudly)."""
        try:
            b.models()
        except BackendError:
            return None
        return b.ckpt

    def _gate_ready(self, addr: str, b: BackendClient) -> None:
        """bootstrap-style readiness gate: /healthz healthy AND
        /v1/models reporting the target checkpoint (when the backend
        reports ckpts at all)."""
        deadline = self._clock() + self.ready_timeout_s
        last_err = "no probe yet"
        while self._clock() < deadline:
            try:
                doc = b.probe()
                b.models()
            except BackendError as e:
                last_err = str(e)
                self._sleep(self.poll_s)
                continue
            if not doc.get("healthy", False):
                last_err = f"unhealthy: {doc.get('status')}"
            elif b.ckpt is not None and b.ckpt != self.ckpt:
                last_err = (
                    f"still reports ckpt {b.ckpt!r} != {self.ckpt!r}"
                )
            else:
                return
            self._sleep(self.poll_s)
        raise RolloutError(
            f"backend {addr} failed the post-reload readiness gate "
            f"after {self.ready_timeout_s:g}s ({last_err})"
        )

    def _resume_quietly(self, addr: str) -> None:
        """Resume without letting a resume failure mask the original
        error (the router may have detached it meanwhile)."""
        try:
            self.admin.resume(addr)
        except RolloutError:
            pass

    # -------------------------------------------------------- braking
    def _slo_brake(self) -> Optional[List[str]]:
        """None when the wave may proceed. On a breach: pause until the
        verdict clears (returns None) or ``pause_timeout_s`` expires /
        ``abort_on_slo`` is set (returns the breach reasons — the
        caller aborts/rolls back)."""
        verdict = self.admin.slo()
        if verdict.get("status") != "degraded":
            return None
        reasons = list(verdict.get("reasons", ()))
        self.paused += 1
        self.admin.note("pause", reasons=reasons)
        if self.abort_on_slo:
            return reasons or ["SLO degraded"]
        deadline = self._clock() + self.pause_timeout_s
        while self._clock() < deadline:
            self._sleep(self.poll_s)
            verdict = self.admin.slo()
            if verdict.get("status") != "degraded":
                self.admin.note("unpause")
                return None
            reasons = list(verdict.get("reasons", ())) or reasons
        raise RolloutError(
            "SLO budgets still breached after "
            f"{self.pause_timeout_s:g}s pause: {reasons}"
        )

    def _abort(self, reasons: List[str]) -> dict:
        """Roll every already-updated backend back to its previous
        checkpoint (newest first), then report aborted."""
        self.admin.note(
            "rollback_started", reasons=reasons,
            backends=[a for a, _ in self.updated],
        )
        rolled, skipped = [], []
        for addr, prev in reversed(self.updated):
            if prev is None:
                skipped.append(addr)
                continue
            try:
                self.admin.drain(addr)
                self._wait_drained(addr)
                b = self.make_backend(addr)
                b.reload(prev)
                self._gate_ready_prev(addr, b, prev)
                rolled.append(addr)
                self.admin.note("rollback_backend", backend=addr,
                                ckpt=prev)
            except (RolloutError, BackendError) as e:
                skipped.append(addr)
                self.admin.note(
                    "reload_failed", backend=addr, error=str(e)
                )
            finally:
                self._resume_quietly(addr)
        self.admin.note("abort", reasons=reasons, rolled_back=rolled)
        return self._report(
            "aborted", reasons=reasons, rolled_back=rolled,
            rollback_skipped=skipped,
        )

    def _gate_ready_prev(self, addr: str, b: BackendClient,
                         prev: str) -> None:
        """Readiness gate against the ROLLBACK target."""
        save = self.ckpt
        self.ckpt = prev
        try:
            self._gate_ready(addr, b)
        finally:
            self.ckpt = save

    # --------------------------------------------------------- report
    def _report(self, status: str, **extra) -> dict:
        out = {
            "status": status,
            "ckpt": self.ckpt,
            "updated": [a for a, _ in self.updated],
            "previous": {a: p for a, p in self.updated},
            "max_unavailable": self.max_unavailable,
            "paused": self.paused,
        }
        out.update(extra)
        return out
